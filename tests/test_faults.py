"""Fault-injection plane + graceful-degradation reactions
(cook_tpu/faults/, utils/retry.py — docs/resilience.md).

Unit level: FaultSchedule rule semantics, the shared retry policy,
circuit-breaker transitions, load shedding / admission scaleback, the
journal fsync policies, follower reconnect backoff, the k8s
idempotent-GET-only retry split.  Integration level (the satellite
coverage the chaos suite complements): a kill racing an open circuit
breaker, an fsync fault during leader failover (acked txns survive on
the promoted standby), and device-fallback cycle parity against the
healthy solve on the same problem.
"""
import os
import tempfile
import time

import pytest
import requests

from cook_tpu import faults
from cook_tpu.faults.breaker import (
    BreakerParams,
    BreakerState,
    CircuitBreaker,
)
from cook_tpu.faults.reactions import AdmissionController, LoadShedder
from cook_tpu.utils.retry import RetryPolicy, backoff_s, call_with_retry


@pytest.fixture(autouse=True)
def _no_schedule_leaks():
    """Every test starts and ends disarmed."""
    faults.disarm()
    yield
    faults.disarm()


# ------------------------------------------------------------- schedule


class TestFaultSchedule:
    def test_unknown_point_and_mode_rejected(self):
        with pytest.raises(ValueError):
            faults.FaultRule(point="journal.fsyncc")
        with pytest.raises(ValueError):
            faults.FaultRule(point=faults.JOURNAL_FSYNC, mode="explode")

    def test_unarmed_site_is_a_noop(self):
        assert faults.ACTIVE is None  # the only check a site pays

    def test_error_rule_rides_oserror_paths(self):
        with faults.injected({"point": "cluster.launch"}):
            with pytest.raises(OSError):
                faults.ACTIVE.hit(faults.CLUSTER_LAUNCH, cluster="c")

    def test_times_bounds_firings(self):
        with faults.injected({"point": "cluster.launch", "times": 2}) as s:
            for _ in range(2):
                with pytest.raises(faults.FaultInjected):
                    s.hit(faults.CLUSTER_LAUNCH)
            s.hit(faults.CLUSTER_LAUNCH)  # exhausted: no raise
            assert s.fired_total() == 2

    def test_after_skips_the_first_hits(self):
        with faults.injected({"point": "device.solve", "after": 2}) as s:
            s.hit(faults.DEVICE_SOLVE)
            s.hit(faults.DEVICE_SOLVE)
            with pytest.raises(faults.FaultInjected):
                s.hit(faults.DEVICE_SOLVE)

    def test_match_filters_on_context(self):
        rule = {"point": "cluster.launch", "match": {"cluster": "sick"}}
        with faults.injected(rule) as s:
            s.hit(faults.CLUSTER_LAUNCH, cluster="healthy")
            with pytest.raises(faults.FaultInjected):
                s.hit(faults.CLUSTER_LAUNCH, cluster="sick")
            # a context that lacks the matched key entirely does not fire
            s.hit(faults.CLUSTER_LAUNCH)
            assert s.fired_total() == 1

    def test_probability_is_seeded_and_deterministic(self):
        def firings(seed):
            schedule = faults.FaultSchedule(
                [faults.FaultRule(point=faults.DEVICE_SOLVE,
                                  probability=0.5)], seed=seed)
            out = []
            for _ in range(20):
                try:
                    schedule.hit(faults.DEVICE_SOLVE)
                    out.append(0)
                except faults.FaultInjected:
                    out.append(1)
            return out

        assert firings(7) == firings(7)  # same seed replays exactly
        assert 0 < sum(firings(7)) < 20  # and actually draws both ways

    def test_delay_mode_sleeps_without_raising(self):
        slept = []
        schedule = faults.FaultSchedule(
            [faults.FaultRule(point=faults.JOURNAL_FSYNC, mode="delay",
                              delay_s=0.25)], sleep=slept.append)
        schedule.hit(faults.JOURNAL_FSYNC)
        assert slept == [0.25]

    def test_injected_nesting_restores_previous_schedule(self):
        with faults.injected({"point": "device.solve"}) as outer:
            with faults.injected({"point": "journal.fsync"}):
                assert faults.ACTIVE is not outer
            assert faults.ACTIVE is outer
        assert faults.ACTIVE is None

    def test_schedule_roundtrips_through_dict(self):
        src = {"seed": 3, "rules": [
            {"point": "k8s.request", "mode": "delay", "delay_s": 0.1,
             "times": 4, "match": {"method": "GET"}}]}
        schedule = faults.FaultSchedule.from_dict(src)
        d = schedule.to_dict()
        assert d["seed"] == 3
        assert d["rules"][0]["point"] == "k8s.request"
        assert d["rules"][0]["match"] == {"method": "GET"}
        assert d["rules"][0]["fired"] == 0


# ---------------------------------------------------------------- retry


class TestRetryPolicy:
    def test_backoff_curve_is_exponential_and_capped(self):
        policy = RetryPolicy(base_s=0.1, multiplier=2.0, cap_s=0.5,
                             jitter=0.0)
        assert backoff_s(policy, 1) == pytest.approx(0.1)
        assert backoff_s(policy, 2) == pytest.approx(0.2)
        assert backoff_s(policy, 3) == pytest.approx(0.4)
        assert backoff_s(policy, 4) == pytest.approx(0.5)  # capped
        assert backoff_s(policy, 10) == pytest.approx(0.5)

    def test_jitter_stays_inside_the_band(self):
        policy = RetryPolicy(base_s=1.0, multiplier=1.0, cap_s=1.0,
                             jitter=0.5)
        import random

        rng = random.Random(5)
        for _ in range(50):
            d = backoff_s(policy, 1, rng)
            assert 0.5 <= d <= 1.0

    def test_retries_then_succeeds(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        slept = []
        out = call_with_retry(flaky,
                              RetryPolicy(max_attempts=3, jitter=0.0,
                                          base_s=0.01),
                              sleep=slept.append)
        assert out == "ok" and calls["n"] == 3
        assert len(slept) == 2

    def test_non_retryable_propagates_immediately(self):
        calls = {"n": 0}

        def wrong():
            calls["n"] += 1
            raise ValueError("not transient")

        with pytest.raises(ValueError):
            call_with_retry(wrong, RetryPolicy(max_attempts=5),
                            sleep=lambda s: None)
        assert calls["n"] == 1

    def test_exhausted_attempts_reraise_the_last_failure(self):
        def dead():
            raise OSError("still down")

        with pytest.raises(OSError, match="still down"):
            call_with_retry(dead,
                            RetryPolicy(max_attempts=3, jitter=0.0,
                                        base_s=0.001),
                            sleep=lambda s: None)

    def test_deadline_bounds_attempts_plus_sleeps(self):
        now = {"t": 0.0}

        def clock():
            return now["t"]

        def sleep(s):
            now["t"] += s

        calls = {"n": 0}

        def dead():
            calls["n"] += 1
            now["t"] += 0.4  # each attempt costs 0.4s
            raise OSError("down")

        # base 0.4s delay + 0.4s attempts against a 1.0s deadline: the
        # second retry would land past the deadline -> stop at 2 calls
        with pytest.raises(OSError):
            call_with_retry(dead,
                            RetryPolicy(max_attempts=10, base_s=0.4,
                                        multiplier=1.0, jitter=0.0,
                                        deadline_s=1.0),
                            sleep=sleep, clock=clock)
        assert calls["n"] == 2


# -------------------------------------------------------------- breaker


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestCircuitBreaker:
    def make(self, **kw):
        clock = _Clock()
        params = BreakerParams(**{"window": 4, "min_samples": 2,
                                  "error_threshold": 0.5,
                                  "cooldown_s": 10.0, **kw})
        return CircuitBreaker("c", params, clock=clock), clock

    def test_no_verdict_below_min_samples(self):
        breaker, _ = self.make(min_samples=3)
        breaker.note_failure()
        breaker.note_failure()
        assert breaker.state is BreakerState.CLOSED

    def test_trips_at_error_threshold(self):
        breaker, _ = self.make(error_threshold=0.6)
        breaker.note_success()
        breaker.note_failure()
        assert breaker.state is BreakerState.CLOSED  # 1/2 < 0.6
        breaker.note_failure()
        assert breaker.state is BreakerState.OPEN    # 2/3 >= 0.6
        assert breaker.opens == 1

    def test_open_blocks_until_cooldown_then_half_open(self):
        breaker, clock = self.make()
        breaker.note_failure()
        breaker.note_failure()
        assert not breaker.allows_work()
        clock.t += 10.0
        assert breaker.allows_work()  # the transition happens here
        assert breaker.state is BreakerState.HALF_OPEN

    def test_half_open_probe_success_closes_and_forgets(self):
        breaker, clock = self.make()
        breaker.note_failure()
        breaker.note_failure()
        clock.t += 10.0
        breaker.allows_work()
        # a successful KILL while half-open is not the probe — only a
        # launch outcome may close the breaker
        breaker.note_success()
        assert breaker.state is BreakerState.HALF_OPEN
        breaker.note_success(probe=True)
        assert breaker.state is BreakerState.CLOSED
        # the pre-open error history described the outage: one new
        # failure must not re-trip on stale errors
        breaker.note_failure()
        assert breaker.state is BreakerState.CLOSED

    def test_half_open_probe_failure_reopens(self):
        breaker, clock = self.make()
        breaker.note_failure()
        breaker.note_failure()
        clock.t += 10.0
        breaker.allows_work()
        breaker.note_failure(probe=True)  # the launch probe failed
        assert breaker.state is BreakerState.OPEN
        assert breaker.opens == 2

    def test_half_open_kill_failure_does_not_retrip(self):
        """Mirror of the kill-success rule: while half-open, only the
        LAUNCH probe's outcome decides the transition.  A cluster with a
        broken kill RPC but healthy launches must not re-trip on every
        ungated kill (it would starve forever — the probe launch could
        never run before a kill failure flipped the breaker back open)."""
        breaker, clock = self.make()
        breaker.note_failure()
        breaker.note_failure()
        clock.t += 10.0
        breaker.allows_work()
        breaker.note_failure()  # a kill failing while half-open
        assert breaker.state is BreakerState.HALF_OPEN
        assert breaker.opens == 1
        breaker.note_success(probe=True)  # the probe launch succeeds
        assert breaker.state is BreakerState.CLOSED

    def test_snapshot_reports_rates(self):
        breaker, _ = self.make()
        breaker.note_success()
        breaker.note_failure()
        snap = breaker.snapshot()
        assert snap["recent_samples"] == 2 and snap["recent_errors"] == 1
        assert snap["error_rate"] == pytest.approx(0.5)


# ----------------------------------------------------- reactions (unit)


class _FakeContention:
    def __init__(self):
        self.reasons = []
        self.evaluations = 0

    def evaluate(self):
        self.evaluations += 1
        return [{"reason": r} for r in self.reasons], {}


class TestLoadShedder:
    def test_sheds_only_on_shed_relevant_reasons(self):
        contention = _FakeContention()
        clock = _Clock()
        shedder = LoadShedder(contention, ttl_s=0.0, clock=clock)
        assert shedder.should_shed("/queue") is None
        contention.reasons = ["fsync-stall"]  # detected but not shed-able
        clock.t += 1
        assert shedder.should_shed("/queue") is None
        contention.reasons = ["commit-ack-slo-burn"]
        clock.t += 1
        verdict = shedder.should_shed("/queue")
        assert verdict is not None
        assert verdict["reasons"] == ["commit-ack-slo-burn"]
        assert verdict["retry_after_s"] > 0

    def test_ttl_caches_the_evaluation(self):
        contention = _FakeContention()
        clock = _Clock()
        shedder = LoadShedder(contention, ttl_s=5.0, clock=clock)
        for _ in range(10):
            shedder.should_shed("/jobs")
        assert contention.evaluations == 1
        clock.t += 6.0
        shedder.should_shed("/jobs")
        assert contention.evaluations == 2


class TestAdmissionController:
    def test_scaleback_floor_and_reset(self):
        overloaded = {"v": True}
        admission = AdmissionController(overload_fn=lambda: overloaded["v"],
                                        scaleback=0.5, floor_fraction=0.1)
        from cook_tpu.scheduler.matcher import PoolMatchState

        state = PoolMatchState(num_considerable=100)
        steps0 = admission._scalebacks.value({"pool": "p"})
        admission.clamp("p", state, 100)
        assert state.num_considerable == 50
        for _ in range(10):  # keep shrinking to the floor, never below
            admission.clamp("p", state, 100)
        assert admission.cap("p") == 10
        # only actual shrink steps count (100->50->25->12->10): a cap
        # held at the floor is not another scaleback
        assert admission._scalebacks.value({"pool": "p"}) - steps0 == 4
        overloaded["v"] = False  # burn clears: cap resets to max
        state.num_considerable = 5  # matcher's own backoff stays OWNED
        admission.clamp("p", state, 100)
        assert admission.cap("p") == 100
        assert state.num_considerable == 5

    def test_broken_overload_signal_fails_open(self):
        def boom():
            raise RuntimeError("signal down")

        admission = AdmissionController(overload_fn=boom)
        from cook_tpu.scheduler.matcher import PoolMatchState

        state = PoolMatchState(num_considerable=100)
        admission.clamp("p", state, 100)  # must not raise
        assert state.num_considerable == 100


# -------------------------------------------------- journal fsync policy


def _journal(tmp_path, **kw):
    from cook_tpu.models.persistence import JournalWriter

    return JournalWriter(os.path.join(str(tmp_path), "journal.jsonl"),
                         fsync_every=0, **kw)


class TestFsyncPolicies:
    def test_fail_stop_reraises_and_notifies(self, tmp_path):
        seen = []
        journal = _journal(tmp_path, on_fsync_error=seen.append)
        journal.write_line('{"kind": "x"}')
        with faults.injected({"point": "journal.fsync"}):
            with pytest.raises(OSError):
                journal.sync()
        assert len(seen) == 1 and isinstance(seen[0], OSError)
        assert not journal.degraded

    def test_degrade_async_keeps_committing_then_recovers(self, tmp_path):
        journal = _journal(tmp_path, fsync_policy="degrade-async",
                           degraded_retry_s=0.05)
        journal.write_line('{"kind": "a"}')
        with faults.injected({"point": "journal.fsync"}):
            journal.sync()  # swallows the failure, degrades
            assert journal.degraded
            assert journal.telemetry.fsync_errors == 1
            # within the cool-off, syncs don't re-probe the broken disk
            journal.write_line('{"kind": "b"}')
            journal.sync()
            assert journal.telemetry.fsync_errors == 1
        time.sleep(0.06)
        journal.sync()  # past the cool-off, the probe succeeds
        assert not journal.degraded
        # everything written while degraded is on disk
        with open(journal.path) as f:
            assert len(f.read().splitlines()) == 2

    def test_unknown_policy_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            _journal(tmp_path, fsync_policy="shrug")


# ------------------------------------------------- replication backoff


class TestFollowerBackoff:
    def make_follower(self):
        from cook_tpu.control.replication import JournalFollower
        from cook_tpu.models.store import JobStore

        return JournalFollower(
            JobStore(), leader_url_fn=lambda: "", poll_s=0.05,
            reconnect_policy=RetryPolicy(base_s=0.1, multiplier=2.0,
                                         cap_s=0.4, jitter=0.0))

    def test_wait_grows_with_failures_and_caps(self):
        follower = self.make_follower()
        assert follower._next_wait_s() == pytest.approx(0.05)
        for _ in range(2):
            follower._transport_error = True
            follower._note_cycle_outcome()
        assert follower._next_wait_s() == pytest.approx(0.2)
        for _ in range(5):
            follower._transport_error = True
            follower._note_cycle_outcome()
        assert follower._next_wait_s() == pytest.approx(0.4)  # capped
        assert follower.reconnect_attempts == 7

    def test_success_resets_to_poll_interval(self):
        follower = self.make_follower()
        follower._transport_error = True
        follower._note_cycle_outcome()
        assert follower._next_wait_s() > 0.05
        follower._note_cycle_outcome()  # clean cycle
        assert follower._next_wait_s() == pytest.approx(0.05)

    def test_dropped_fetch_counts_reconnects(self):
        """The replication.fetch fault point drives the REAL loop: a
        dead leader produces counted, backed-off reconnect attempts."""
        follower = self.make_follower()
        follower.leader_url_fn = lambda: "http://127.0.0.1:1"
        with faults.injected({"point": "replication.fetch"}):
            follower.start()
            deadline = time.monotonic() + 5.0
            while follower.reconnect_attempts < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            follower.stop()
        assert follower.reconnect_attempts >= 2

    def test_apply_failure_is_not_a_reconnect(self):
        """A sync_once raise that is NOT a transport error (e.g. a store
        apply bug) must retry at the normal poll cadence — no reconnect
        count, no backoff stretching replication lag to the cap."""
        follower = self.make_follower()
        follower.leader_url_fn = lambda: "http://leader.example"
        follower.sync_once = lambda: (_ for _ in ()).throw(
            RuntimeError("apply failed"))
        follower.start()
        time.sleep(0.4)  # several poll cycles' worth of failures
        follower.stop()
        assert follower.reconnect_attempts == 0
        assert follower._next_wait_s() == pytest.approx(follower.poll_s)


# ------------------------------------------------------- k8s retry split


class TestK8sRetrySplit:
    @pytest.fixture()
    def api(self):
        from cook_tpu.cluster.k8s_http import HttpKubeApi
        from tests.fake_apiserver import make_server

        server, state, url = make_server()
        api = HttpKubeApi(url, namespace="default")
        state.add_node("n1", 8192, 16)
        yield api
        api.stop()
        server.shutdown()

    def test_idempotent_get_retried_once(self, api):
        with faults.injected({"point": "k8s.request", "times": 1,
                              "match": {"method": "GET"}}) as s:
            [node] = api.list_nodes()  # first attempt faulted, retry won
        assert node.name == "n1"
        assert s.fired_total() == 1

    def test_mutating_request_stays_single_shot(self, api):
        with faults.injected({"point": "k8s.request", "times": 1,
                              "match": {"method": "DELETE"}}) as s:
            # if DELETE were retried, the second attempt would succeed
            # and no error would surface — the raise IS the proof
            with pytest.raises(OSError):
                api.delete_pod("anything")
        assert s.fired_total() == 1

    def test_get_retry_classification(self):
        from cook_tpu.cluster.k8s_http import (
            ApiError,
            WatchGap,
            _retryable_get_error,
        )

        assert _retryable_get_error(OSError("conn refused"))
        assert _retryable_get_error(ApiError("boom", 503))
        assert not _retryable_get_error(ApiError("bad request", 400))
        assert not _retryable_get_error(ApiError("not found", 404))
        assert not _retryable_get_error(WatchGap("/pods"))
        assert not _retryable_get_error(ValueError("bad json"))


# --------------------------------------- scheduler-level breaker + kill


def _scheduler_rig(n_hosts=4, n_jobs=6, fallback_cycles=8):
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.entities import Job, Pool, Resources
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from cook_tpu.scheduler.matcher import MatchConfig
    from tests.conftest import FakeClock

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    hosts = [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4000, cpus=8)
             for i in range(n_hosts)]
    cluster = MockCluster("mock", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=0,
                          device_fallback_cycles=fallback_cycles)))
    # deterministic uuids: the parity test compares placements across
    # two independent rigs built from this same trace
    jobs = [Job(uuid=f"flt-{i:03d}", user=f"u{i % 3}", pool="default",
                command="true", resources=Resources(mem=200, cpus=1),
                max_retries=5)
            for i in range(n_jobs)]
    store.submit_jobs(jobs)
    return clock, store, cluster, scheduler, jobs


def _match_once(scheduler, store, clock):
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    clock.advance(1000)
    return outcome


class TestBreakerIntegration:
    def test_kill_races_an_open_breaker(self):
        """Kills are NEVER gated: a job killed while its cluster's
        breaker is open still reaches the backend, and recovery does not
        resurrect it or double-launch anything."""
        from cook_tpu.models.entities import JobState

        clock, store, cluster, scheduler, jobs = _scheduler_rig()
        _match_once(scheduler, store, clock)
        assert all(store.jobs[j.uuid].state is JobState.RUNNING
                   for j in jobs)

        breaker = cluster.configure_breaker(BreakerParams(
            window=4, min_samples=2, error_threshold=0.5, cooldown_s=0.2))
        from tests.conftest import make_job

        late = [make_job(user="late", mem=200, cpus=1, max_retries=5)
                for _ in range(2)]
        store.submit_jobs(late)
        with faults.injected({"point": "cluster.launch", "times": 2,
                              "match": {"cluster": "mock"}}):
            _match_once(scheduler, store, clock)
            _match_once(scheduler, store, clock)
        assert breaker.state is BreakerState.OPEN

        # the race: a user kill lands while the breaker is OPEN
        victim = jobs[0]
        task_ids = {i.task_id for i in
                    store.live_instances_of_job(victim.uuid)}
        store.kill_jobs([victim.uuid])
        assert store.jobs[victim.uuid].state is JobState.COMPLETED
        assert not any(t in cluster.running for t in task_ids), \
            "open breaker blocked the kill RPC"
        assert breaker.state is BreakerState.OPEN  # kills don't close it

        time.sleep(0.25)  # cooldown -> the next launch is the probe
        for _ in range(4):
            _match_once(scheduler, store, clock)
            if all(store.jobs[j.uuid].state is JobState.RUNNING
                   for j in late):
                break
        assert breaker.state is BreakerState.CLOSED
        assert store.jobs[victim.uuid].state is JobState.COMPLETED
        live = [i for i in store.instances.values()
                if not i.status.terminal]
        assert len({i.task_id for i in live}) == len(live)
        assert set(cluster.running) == {i.task_id for i in live}

    def test_open_breaker_skips_with_circuit_reason(self):
        from cook_tpu.scheduler import flight_recorder as flight_codes

        clock, store, cluster, scheduler, jobs = _scheduler_rig(n_jobs=3)
        cluster.configure_breaker(BreakerParams(
            window=4, min_samples=2, error_threshold=0.5,
            cooldown_s=60.0))
        with faults.injected({"point": "cluster.launch", "times": 2}):
            _match_once(scheduler, store, clock)
            _match_once(scheduler, store, clock)
        launched = len(store.instances)
        _match_once(scheduler, store, clock)  # open: no offers, no txns
        assert len(store.instances) == launched
        reason = scheduler.recorder.job_reason(jobs[0].uuid)
        assert reason is not None
        assert reason[1] == flight_codes.CLUSTER_CIRCUIT_OPEN

    def test_offer_scan_failure_skips_cluster_not_cycle(self):
        from cook_tpu.models.entities import JobState

        clock, store, cluster, scheduler, jobs = _scheduler_rig(n_jobs=2)
        with faults.injected({"point": "cluster.offers", "times": 1}):
            _match_once(scheduler, store, clock)  # scan raised: skipped
        _match_once(scheduler, store, clock)
        assert all(store.jobs[j.uuid].state is JobState.RUNNING
                   for j in jobs)


# --------------------------------------------------- device fallback


class TestDeviceFallback:
    def test_fallback_cycle_parity_with_healthy_solve(self):
        """The CPU-fallback cycle places exactly what the healthy device
        solve places on the same problem — no cycle is lost, no
        placement diverges."""
        _, store_a, _, sched_a, _ = _scheduler_rig(n_hosts=3, n_jobs=6,
                                                   fallback_cycles=2)
        clock_b, store_b, _, sched_b, jobs = _scheduler_rig(
            n_hosts=3, n_jobs=6, fallback_cycles=2)
        pool_a = store_a.pools["default"]
        sched_a.rank_cycle(pool_a)
        healthy = sched_a.match_cycle(pool_a)
        with faults.injected({"point": "device.solve", "times": 1}):
            degraded = _match_once(sched_b, store_b, clock_b)
        assert len(degraded.matched) == len(jobs)
        a = {(j.uuid, o.hostname) for j, o in healthy.matched}
        b = {(j.uuid, o.hostname) for j, o in degraded.matched}
        assert a == b

    def test_health_reason_raised_then_cleared_by_probe(self):
        clock, store, _, scheduler, jobs = _scheduler_rig(
            n_hosts=3, n_jobs=4, fallback_cycles=2)
        from tests.conftest import make_job

        with faults.injected({"point": "device.solve", "times": 1}):
            _match_once(scheduler, store, clock)
        assert "device-degraded" in scheduler.telemetry.health()["reasons"]
        for cycle in range(3):  # keep the pool solvable through the window
            store.submit_jobs([make_job(user="x", mem=100, cpus=0.5,
                                        max_retries=5)])
            _match_once(scheduler, store, clock)
        assert "device-degraded" not in \
            scheduler.telemetry.health()["reasons"]

    def test_fallback_disabled_propagates_the_error(self):
        clock, store, _, scheduler, jobs = _scheduler_rig(
            n_jobs=2, fallback_cycles=0)
        with faults.injected({"point": "device.solve", "times": 1}):
            with pytest.raises(OSError):
                _match_once(scheduler, store, clock)


def _multi_pool_rig(n_pools=3, fallback_cycles=2):
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.entities import Job, Pool, Resources
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from cook_tpu.scheduler.matcher import MatchConfig
    from tests.conftest import FakeClock

    clock = FakeClock()
    store = JobStore(clock=clock)
    hosts = []
    for p in range(n_pools):
        store.set_pool(Pool(name=f"pool{p}"))
        hosts.append(MockHost(node_id=f"p{p}h0", hostname=f"p{p}h0",
                              mem=4000, cpus=8, pool=f"pool{p}"))
    cluster = MockCluster("mock", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=0,
                          device_fallback_cycles=fallback_cycles)))
    jobs = [Job(uuid=f"bat-{p}-{i}", user=f"u{i % 2}", pool=f"pool{p}",
                command="true", resources=Resources(mem=200, cpus=1),
                max_retries=5)
            for p in range(n_pools) for i in range(3)]
    store.submit_jobs(jobs)
    return clock, store, cluster, scheduler, jobs


class TestBatchedDeviceFallback:
    """The batched multi-pool path carries the same device.solve fault
    point and reaction (c) as the per-pool and pipelined paths: a sick
    device fails the SHARED solve, so every participating pool re-solves
    host-side the same cycle and degrades until its probe."""

    def test_batched_fault_degrades_all_pools_cycle_survives(self):
        _, store_a, _, sched_a, _ = _multi_pool_rig()
        clock_b, store_b, _, sched_b, jobs = _multi_pool_rig()
        healthy = sched_a.match_cycle_all_pools()
        with faults.injected({"point": "device.solve", "times": 1}):
            degraded = sched_b.match_cycle_all_pools()
        total = sum(len(o.matched) for o in degraded.values())
        assert total == len(jobs)  # no cycle lost to the sick device
        for name in healthy:  # placement parity with the healthy batch
            a = {(j.uuid, o.hostname) for j, o in healthy[name].matched}
            b = {(j.uuid, o.hostname) for j, o in degraded[name].matched}
            assert a == b
        reasons = sched_b.telemetry.health()["reasons"]
        assert "device-degraded" in reasons

    def test_batched_probe_clears_the_degradation(self):
        from tests.conftest import make_job

        clock, store, _, scheduler, jobs = _multi_pool_rig(
            n_pools=2, fallback_cycles=1)
        with faults.injected({"point": "device.solve", "times": 1}):
            scheduler.match_cycle_all_pools()
        assert "device-degraded" in scheduler.telemetry.health()["reasons"]
        for cycle in range(2):  # burn the budget, then the probe batch
            # keep BOTH pools solvable: only a solvable pool consumes
            # fallback budget and joins the probing batch
            store.submit_jobs([make_job(user="x", pool=f"pool{p}", mem=100,
                                        cpus=0.5, max_retries=5)
                               for p in range(2)])
            clock.advance(1000)
            for pool in store.pools.values():  # re-rank the new jobs in
                scheduler.rank_cycle(pool)
            scheduler.match_cycle_all_pools()
        assert "device-degraded" not in \
            scheduler.telemetry.health()["reasons"]


class TestSimulatorFaultNesting:
    def test_sim_run_restores_the_outer_schedule(self):
        """Simulator.run arms SimConfig.fault_schedule; finishing must
        RESTORE a schedule armed by an enclosing faults.injected block
        (the nesting contract injected.__exit__ documents), not disarm
        the whole plane out from under the outer block."""
        from cook_tpu.sim.simulator import (
            SimConfig,
            Simulator,
            TraceHost,
            TraceJob,
        )

        jobs = [TraceJob(uuid="j0", user="u", submit_time_ms=0,
                         runtime_ms=1000, mem=100, cpus=1)]
        hosts = [TraceHost(node_id="n0", hostname="n0", mem=2000, cpus=4)]
        sim = Simulator(jobs, hosts, SimConfig(
            cycle_ms=1000, max_cycles=4,
            fault_schedule={"rules": [{"point": "cluster.offers",
                                       "mode": "error", "times": 1}]}))
        with faults.injected({"point": "cluster.kill", "mode": "error"}):
            outer = faults.ACTIVE
            sim.run()
            assert faults.ACTIVE is outer  # restored, not disarmed


class TestElasticOffersGuard:
    def test_flapping_offers_rpc_skips_cluster_not_commit_path(self):
        """CapacityPlanner.reconcile runs after EVERY capacity commit: a
        raising offers RPC in its scale-target scan must skip the
        cluster (the safe_pool_offers guard), not crash the commit path
        — and the cluster.offers fault point reaches the elastic plane."""
        from cook_tpu.cluster.mock import MockCluster, MockHost
        from cook_tpu.elastic import CapacityPlanner, ElasticParams
        from cook_tpu.models.entities import Pool
        from cook_tpu.models.store import JobStore
        from cook_tpu.txn import TransactionLog
        from tests.conftest import FakeClock

        clock = FakeClock()
        store = JobStore(clock=clock)
        store.set_pool(Pool(name="default"))
        cluster = MockCluster("m", [
            MockHost(node_id="h0", hostname="h0", mem=4000, cpus=8)],
            clock=clock)
        planner = CapacityPlanner(store, [cluster], TransactionLog(store),
                                  ElasticParams(enabled=True))
        with faults.injected({"point": "cluster.offers", "mode": "error"}):
            planner.reconcile()  # must not raise


# ------------------------------------------- fsync during leader failover


class TestFailoverFsync:
    def test_acked_txns_survive_on_the_promoted_standby(self):
        """The leader's disk dies mid-fsync (fail-stop): the failing
        commit errors to its client, and every PREVIOUSLY acked txn is
        recoverable from the durable standby's local journal."""
        from cook_tpu.control.replication import JournalFollower
        from cook_tpu.models import persistence
        from cook_tpu.models.store import JobStore
        from cook_tpu.rest.server import InprocessControlPlane

        follower_dir = tempfile.mkdtemp(prefix="cook-faults-standby-")
        cp = InprocessControlPlane().start()
        store2 = JobStore()
        journal2 = persistence.attach_journal(
            store2, os.path.join(follower_dir, "journal.jsonl"))
        follower = JournalFollower(
            store2, leader_url_fn=lambda: cp.url,
            self_url="http://standby", member_id="standby",
            data_dir=follower_dir, journal=journal2,
            poll_s=0.05, timeout_s=2.0, long_poll_s=0.1).start()
        try:
            headers = {"X-Cook-Requesting-User": "admin"}
            acked = []
            for i in range(5):
                r = requests.post(f"{cp.url}/jobs", json={"jobs": [{
                    "uuid": f"fo-{i}", "command": "true", "mem": 64,
                    "cpus": 0.1}]}, headers=headers)
                assert r.status_code == 201
                acked.append(f"fo-{i}")
            deadline = time.monotonic() + 5.0
            while store2.last_seq() != cp.store.last_seq() \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert store2.last_seq() == cp.store.last_seq()

            # only the LEADER's journal is matched — the standby's disk
            # (same process) stays healthy
            with faults.injected({"point": "journal.fsync",
                                  "match": {"path": cp.journal.path}}):
                r = requests.post(f"{cp.url}/jobs", json={"jobs": [{
                    "uuid": "fo-doomed", "command": "true", "mem": 64,
                    "cpus": 0.1}]}, headers=headers)
                assert r.status_code >= 500  # undurable = not acked

            cp.server.stop()  # the leader dies
            follower.stop()
            journal2.sync()
            journal2.close()
            promoted = persistence.recover(follower_dir)
            assert promoted is not None
            assert all(uuid in promoted.jobs for uuid in acked)
        finally:
            follower.stop()
            cp.stop()
            import shutil

            shutil.rmtree(follower_dir, ignore_errors=True)


# ------------------------------------------------------- REST endpoint


class TestFaultEndpoint:
    def test_disabled_by_default(self):
        from cook_tpu.rest.server import InprocessControlPlane

        cp = InprocessControlPlane().start()
        try:
            headers = {"X-Cook-Requesting-User": "admin"}
            assert requests.get(f"{cp.url}/debug/faults",
                                headers=headers).status_code == 403
            assert requests.post(
                f"{cp.url}/debug/faults", json={"rules": []},
                headers=headers).status_code == 403
        finally:
            cp.stop()

    def test_arm_observe_disarm(self):
        from cook_tpu.rest.api import ApiConfig
        from cook_tpu.rest.server import InprocessControlPlane

        cp = InprocessControlPlane(
            config=ApiConfig(fault_injection=True)).start()
        try:
            admin = {"X-Cook-Requesting-User": "admin"}
            schedule = {"seed": 1, "rules": [
                {"point": "journal.fsync", "mode": "delay",
                 "delay_s": 0.0}]}
            # non-admin cannot arm
            r = requests.post(f"{cp.url}/debug/faults", json=schedule,
                              headers={"X-Cook-Requesting-User": "mal"})
            assert r.status_code == 403 and faults.ACTIVE is None
            r = requests.post(f"{cp.url}/debug/faults", json=schedule,
                              headers=admin)
            assert r.status_code == 200 and r.json()["armed"]
            assert faults.ACTIVE is not None
            # a commit crosses the armed (zero-delay) fsync point
            r = requests.post(f"{cp.url}/jobs", json={"jobs": [{
                "uuid": "armed-1", "command": "true", "mem": 64,
                "cpus": 0.1}]}, headers=admin)
            assert r.status_code == 201
            status = requests.get(f"{cp.url}/debug/faults",
                                  headers=admin).json()
            assert status["armed"]
            assert status["schedule"]["rules"][0]["fired"] >= 1
            r = requests.post(f"{cp.url}/debug/faults",
                              json={"disarm": True}, headers=admin)
            assert r.status_code == 200 and not r.json()["armed"]
            assert faults.ACTIVE is None
            bad = {"rules": [{"point": "not.a.point"}]}
            assert requests.post(f"{cp.url}/debug/faults", json=bad,
                                 headers=admin).status_code == 400
        finally:
            cp.stop()
