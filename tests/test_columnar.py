"""Columnar index consistency + columnar-vs-loop rank parity + speed."""
import time

import numpy as np
import pytest

from cook_tpu.models.columnar import ColumnarJobIndex
from cook_tpu.models.entities import (
    DEFAULT_USER,
    InstanceStatus,
    Pool,
    Quota,
    Resources,
    Share,
)
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.ranking import rank_pool
from cook_tpu.scheduler.ranking_columnar import rank_pool_columnar
from tests.conftest import FakeClock, make_job


def build_store(clock, n_jobs=300, n_users=7, seed=5, with_running=True):
    rng = np.random.default_rng(seed)
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=1000, cpus=10, gpus=1)))
    jobs = []
    for i in range(n_jobs):
        jobs.append(make_job(
            user=f"u{rng.integers(n_users)}",
            mem=float(rng.choice([64, 128, 256])),
            cpus=float(rng.choice([1, 2])),
            priority=int(rng.choice([25, 50, 75])),
        ))
    store.submit_jobs(jobs)
    if with_running:
        for k, job in enumerate(jobs[: n_jobs // 4]):
            store.create_instance(job.uuid, f"t{k}", hostname=f"h{k % 9}")
            clock.advance(7)
    return store, jobs


def test_index_tracks_store_through_lifecycle(clock):
    store, jobs = build_store(clock)
    index = ColumnarJobIndex(store)
    assert index.consistent_with_store()
    # completions, kills, retries keep it consistent
    for k in range(30):
        store.update_instance_state(
            f"t{k}",
            InstanceStatus.SUCCESS if k % 2 else InstanceStatus.FAILED,
            1000 if k % 2 else 99000,
        )
    store.kill_jobs([jobs[-1].uuid, jobs[-2].uuid])
    assert index.consistent_with_store()
    # new submissions after attach
    more = [make_job(user="late") for _ in range(5)]
    store.submit_jobs(more)
    store.create_instance(more[0].uuid, "late-t", hostname="h1")
    assert index.consistent_with_store()
    pending, live = index.pool_view("default")
    want_pending = {j.uuid for j in store.pending_jobs("default")}
    assert {index.uuids[r] for r in pending} == want_pending


def test_index_rebuild_matches_incremental(clock):
    store, jobs = build_store(clock)
    incremental = ColumnarJobIndex(store)
    for k in range(20):
        store.update_instance_state(f"t{k}", InstanceStatus.SUCCESS, 1000)
    fresh = ColumnarJobIndex(store)
    p1, i1 = incremental.pool_view("default")
    p2, i2 = fresh.pool_view("default")
    assert {incremental.uuids[r] for r in p1} == {fresh.uuids[r] for r in p2}
    assert len(i1) == len(i2)


def queue_signature(store, queue):
    """Comparable view: per-user relative order + per-job dru."""
    per_user = {}
    for job in queue.jobs:
        per_user.setdefault(job.user, []).append(job.uuid)
    return per_user, {u: round(d, 4) for u, d in queue.dru.items()}


def test_columnar_rank_parity(clock):
    store, jobs = build_store(clock)
    # add quotas so capping paths engage
    store.set_quota(Quota(user="u1", pool="default",
                          resources=Resources(mem=400, cpus=4, gpus=0),
                          count=3))
    index = ColumnarJobIndex(store)
    pool = store.pools["default"]
    loop_q = rank_pool(store, pool)
    col_q = rank_pool_columnar(store, index, pool)
    assert {j.uuid for j in loop_q.jobs} == {j.uuid for j in col_q.jobs}
    assert sorted(loop_q.capped) == sorted(col_q.capped)
    lp, ld = queue_signature(store, loop_q)
    cp, cd = queue_signature(store, col_q)
    assert lp == cp   # identical per-user order
    assert ld == cd   # identical drus


def test_columnar_rank_parity_with_offensive_filter(clock):
    store, jobs = build_store(clock, with_running=False)
    monster = make_job(mem=99999.0)
    store.submit_jobs([monster])
    index = ColumnarJobIndex(store)
    pool = store.pools["default"]
    col_q = rank_pool_columnar(store, index, pool,
                               capacity_limits=(1000.0, 50.0, 0.0))
    assert monster.uuid in col_q.quarantined
    assert all(j.uuid != monster.uuid for j in col_q.jobs)


def test_columnar_rank_speed(clock):
    """20k pending jobs: the columnar path must encode in well under the
    loop path's time (sanity bound, not a strict benchmark).

    Deflaked for concurrent CPU load (the full tier-1 run executes
    alongside other CPU-heavy tests): both paths are timed best-of-3 in
    the SAME process — min-of-N is robust to scheduler preemption
    because external load only ever ADDS wall time to a sample — and
    the comparison is a work ratio against that same-process baseline,
    not a wall-clock constant."""
    store, jobs = build_store(clock, n_jobs=20000, n_users=40,
                              with_running=False)
    index = ColumnarJobIndex(store)
    pool = store.pools["default"]
    rank_pool_columnar(store, index, pool)  # warm the kernel
    rank_pool(store, pool)                  # warm the loop path too

    def best_of(fn, n=3):
        best, result = float("inf"), None
        for _ in range(n):
            t0 = time.perf_counter()
            result = fn()
            best = min(best, time.perf_counter() - t0)
        return best, result

    col_s, col_q = best_of(lambda: rank_pool_columnar(store, index, pool))
    loop_s, loop_q = best_of(lambda: rank_pool(store, pool))
    assert len(col_q.jobs) == len(loop_q.jobs) == 20000
    assert col_s < loop_s, (col_s, loop_s)


def test_index_tracks_retry_revival(clock):
    """A job revived via retry must re-enter the columnar pending view
    (regression: retry emitted no job/state event, stranding the index)."""
    from cook_tpu.models.entities import InstanceStatus

    store, jobs = build_store(clock, n_jobs=3, with_running=False)
    index = ColumnarJobIndex(store)
    job = jobs[0]
    store.create_instance(job.uuid, "rt1", hostname="h1")
    store.update_instance_state("rt1", InstanceStatus.FAILED, 99000)
    assert store.jobs[job.uuid].state.value == "completed"
    assert index.consistent_with_store()
    store.retry_job(job.uuid, 5)
    assert store.jobs[job.uuid].state.value == "waiting"
    assert index.consistent_with_store()
    pending, _ = index.pool_view("default")
    assert job.uuid in {index.uuids[r] for r in pending}
