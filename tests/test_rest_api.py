"""REST API integration tests over a real HTTP server (the role of the
reference's test/cook/test/rest/api.clj + integration test_basic.py)."""
import pytest
import requests

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread
from cook_tpu.scheduler.core import Scheduler
from tests.conftest import FakeClock


@pytest.fixture(scope="module")
def server():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    store.set_pool(Pool(name="gpu-pool"))
    cluster = MockCluster(
        "mock",
        [MockHost(node_id=f"n{i}", hostname=f"n{i}", mem=4096, cpus=16)
         for i in range(4)],
        clock=clock,
    )
    scheduler = Scheduler(store, [cluster])
    api = CookApi(store, scheduler, ApiConfig(admins=("admin",)))
    srv = ServerThread(api).start()
    srv.clock = clock
    srv.store = store
    srv.scheduler = scheduler
    srv.cluster = cluster
    yield srv
    srv.stop()


def hdr(user="alice"):
    return {"X-Cook-Requesting-User": user}


def submit(server, jobs, user="alice", groups=None, expect=201):
    body = {"jobs": jobs}
    if groups:
        body["groups"] = groups
    r = requests.post(f"{server.url}/jobs", json=body, headers=hdr(user))
    assert r.status_code == expect, r.text
    return r.json()


def test_submit_and_query_job(server):
    out = submit(server, [{"command": "echo hi", "mem": 100, "cpus": 1,
                           "uuid": "11111111-0000-0000-0000-000000000001"}])
    uuid = out["jobs"][0]
    r = requests.get(f"{server.url}/jobs/{uuid}", headers=hdr())
    assert r.status_code == 200
    job = r.json()
    assert job["status"] == "waiting"
    assert job["user"] == "alice"
    assert job["mem"] == 100
    # query by user
    r = requests.get(f"{server.url}/jobs", params={"user": "alice"},
                     headers=hdr())
    assert any(j["uuid"] == uuid for j in r.json())


def test_submit_validation_errors(server):
    submit(server, [{"mem": 100, "cpus": 1}], expect=400)  # no command
    submit(server, [{"command": "x", "mem": -5}], expect=400)
    submit(server, [{"command": "x", "cpus": 99999}], expect=400)
    submit(server, [{"command": "x", "priority": 500}], expect=400)
    submit(server, [{"command": "x", "pool": "nope"}], expect=400)
    r = requests.post(f"{server.url}/jobs", json={"jobs": []}, headers=hdr())
    assert r.status_code == 400


def test_duplicate_uuid_rejected(server):
    spec = {"command": "x", "uuid": "22222222-0000-0000-0000-000000000002"}
    submit(server, [spec])
    submit(server, [spec], expect=400)


def test_kill_job_authz(server):
    uuid = submit(server, [{"command": "sleep"}], user="bob")["jobs"][0]
    # alice may not kill bob's job
    r = requests.delete(f"{server.url}/jobs", params={"job": uuid},
                        headers=hdr("alice"))
    assert r.status_code == 403
    # admin may
    r = requests.delete(f"{server.url}/jobs", params={"job": uuid},
                        headers=hdr("admin"))
    assert r.status_code == 204
    r = requests.get(f"{server.url}/jobs/{uuid}", headers=hdr())
    assert r.json()["status"] == "completed"


def test_impersonation(server):
    uuid = submit(server, [{"command": "sleep"}], user="carol")["jobs"][0]
    headers = {"X-Cook-Requesting-User": "admin",
               "X-Cook-Impersonate": "carol"}
    r = requests.delete(f"{server.url}/jobs", params={"job": uuid},
                        headers=headers)
    assert r.status_code == 204
    # non-admin cannot impersonate
    headers = {"X-Cook-Requesting-User": "bob",
               "X-Cook-Impersonate": "carol"}
    r = requests.get(f"{server.url}/jobs", params={"user": "carol"},
                     headers=headers)
    assert r.status_code == 403


def test_share_quota_endpoints(server):
    r = requests.post(f"{server.url}/share", json={
        "user": "default", "share": {"mem": 1000, "cpus": 10, "gpus": 1}},
        headers=hdr("admin"))
    assert r.status_code == 201
    r = requests.get(f"{server.url}/share", params={"user": "dave"},
                     headers=hdr())
    assert r.json()["mem"] == 1000
    r = requests.post(f"{server.url}/quota", json={
        "user": "dave", "quota": {"count": 3, "mem": 500, "cpus": 5}},
        headers=hdr("admin"))
    assert r.status_code == 201
    r = requests.get(f"{server.url}/quota", params={"user": "dave"},
                     headers=hdr())
    assert r.json()["count"] == 3
    r = requests.delete(f"{server.url}/quota", params={"user": "dave"},
                        headers=hdr("admin"))
    assert r.status_code == 204


def test_retry_endpoint(server):
    uuid = submit(server, [{"command": "x", "max_retries": 1}])["jobs"][0]
    r = requests.get(f"{server.url}/retry", params={"job": uuid}, headers=hdr())
    assert r.json() == 1
    r = requests.post(f"{server.url}/retry",
                      json={"job": uuid, "retries": 5}, headers=hdr())
    assert r.status_code == 201
    r = requests.get(f"{server.url}/retry", params={"job": uuid}, headers=hdr())
    assert r.json() == 5


def test_groups_endpoint(server):
    guuid = "33333333-0000-0000-0000-000000000003"
    submit(server, [
        {"command": "x", "group": guuid},
        {"command": "y", "group": guuid},
    ], groups=[{"uuid": guuid, "host_placement": {"type": "unique"}}])
    r = requests.get(f"{server.url}/group",
                     params=[("uuid", guuid), ("detailed", "true")],
                     headers=hdr())
    g = r.json()[0]
    assert g["host_placement"]["type"] == "unique"
    assert len(g["jobs"]) == 2
    assert g["composition"]["waiting"] == 2


def test_full_lifecycle_via_api(server):
    """submit -> match cycle -> running -> complete -> query"""
    uuid = submit(server, [{"command": "work", "mem": 100, "cpus": 1,
                            "expected_runtime": 50_000}])["jobs"][0]
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    r = requests.get(f"{server.url}/jobs/{uuid}", headers=hdr())
    assert r.json()["status"] == "running"
    assert len(r.json()["instances"]) == 1
    inst = r.json()["instances"][0]
    assert inst["status"] == "running"
    # progress update (sidecar path)
    r = requests.post(f"{server.url}/progress/{inst['task_id']}",
                      json={"progress_percent": 50,
                            "progress_message": "half"},
                      headers=hdr())
    assert r.status_code == 202
    r = requests.get(f"{server.url}/progress/{inst['task_id']}", headers=hdr())
    assert r.json() == {"progress": 50, "progress_message": "half"}
    # usage shows the running job
    r = requests.get(f"{server.url}/usage", params={"user": "alice"},
                     headers=hdr())
    assert r.json()["total_usage"]["jobs"] >= 1
    # complete it
    server.clock.advance(60_000)
    server.cluster.advance_to(server.clock.now_ms)
    r = requests.get(f"{server.url}/jobs/{uuid}", headers=hdr())
    assert r.json()["status"] == "completed"
    assert r.json()["instances"][0]["status"] == "success"


def test_unscheduled_reasons(server):
    uuid = submit(server, [{"command": "x", "mem": 999999999, "cpus": 1,
                            "max_retries": 1}], expect=400)
    uuid = submit(server, [{"command": "x", "mem": 400000, "cpus": 400}])["jobs"][0]
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    r = requests.get(f"{server.url}/unscheduled_jobs", params={"job": uuid},
                     headers=hdr())
    reasons = r.json()[0]["reasons"]
    assert any("placed" in x["reason"] or "queue" in x["reason"]
               for x in reasons), reasons


def test_info_pools_settings_reasons_metrics(server):
    assert requests.get(f"{server.url}/info", headers=hdr()).status_code == 200
    pools = requests.get(f"{server.url}/pools", headers=hdr()).json()
    assert {p["name"] for p in pools} == {"default", "gpu-pool"}
    settings = requests.get(f"{server.url}/settings", headers=hdr()).json()
    assert "max-job-mem" in settings
    reasons = requests.get(f"{server.url}/failure_reasons", headers=hdr()).json()
    assert any(r["code"] == 1002 and r["mea_culpa"] for r in reasons)
    metrics = requests.get(f"{server.url}/metrics", headers=hdr())
    assert "cook_jobs_submitted" in metrics.text


def test_dynamic_cluster_endpoint(server):
    r = requests.get(f"{server.url}/compute-clusters", headers=hdr())
    configs = r.json()["in-mem-configs"]
    assert configs[0]["name"] == "mock"
    assert configs[0]["state"] == "running"
    # non-admin cannot change state
    r = requests.post(f"{server.url}/compute-clusters",
                      json={"name": "mock", "state": "draining"},
                      headers=hdr("bob"))
    assert r.status_code == 403
    r = requests.post(f"{server.url}/compute-clusters",
                      json={"name": "mock", "state": "draining"},
                      headers=hdr("admin"))
    assert r.status_code == 201
    # draining cluster gives no offers to the matcher
    assert not server.scheduler.clusters[0].accepts_work
    r = requests.post(f"{server.url}/compute-clusters",
                      json={"name": "mock", "state": "running"},
                      headers=hdr("admin"))
    assert r.status_code == 201


def test_queue_endpoint(server):
    submit(server, [{"command": "q", "mem": 100, "cpus": 1}])
    server.scheduler.rank_cycle(server.store.pools["default"])
    r = requests.get(f"{server.url}/queue", headers=hdr())
    assert "default" in r.json()


def test_container_application_checkpoint_parsing(server):
    out = submit(server, [{
        "command": "x",
        "container": {"type": "DOCKER",
                      "docker": {"image": "repo/img:v1"}},
        "application": {"name": "spark", "version": "3.0"},
        "checkpoint": {"mode": "auto", "location": "us-east"},
    }])
    job = server.store.jobs[out["jobs"][0]]
    assert job.container.image == "repo/img:v1"
    assert job.application.name == "spark"
    assert job.checkpoint.location == "us-east"
    # application is exposed back through the query API (rest/api.clj
    # fetch-job-map includes :application)
    r = requests.get(f"{server.url}/jobs/{job.uuid}", headers=hdr())
    assert r.json()["application"] == {
        "name": "spark", "version": "3.0",
        "workload-class": "", "workload-id": "",
    }


def test_cancel_instance_endpoint(server):
    uuid = submit(server, [{"command": "c", "mem": 100, "cpus": 1,
                            "max_retries": 3}])["jobs"][0]
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    [inst] = server.store.job_instances(uuid)
    # another user may not cancel
    r = requests.delete(f"{server.url}/instances",
                        params={"instance": inst.task_id}, headers=hdr("eve"))
    assert r.status_code == 403
    r = requests.delete(f"{server.url}/instances",
                        params={"instance": inst.task_id}, headers=hdr())
    assert r.status_code == 204
    assert server.store.instances[inst.task_id].status.value == "failed"
    # the job retries (cancel kills the instance, not the job)
    assert server.store.jobs[uuid].state.value == "waiting"


def test_dynamic_cluster_creation(server):
    """POST /compute-clusters with a kind creates and attaches a new
    cluster whose offers join the next match cycle."""
    r = requests.post(f"{server.url}/compute-clusters", json={
        "kind": "mock",
        "name": "burst-cluster",
        "hosts": [{"node_id": "bx0", "mem": 9000, "cpus": 64}],
    }, headers=hdr("admin"))
    assert r.status_code == 201, r.text
    names = [c["name"] for c in requests.get(
        f"{server.url}/compute-clusters", headers=hdr()).json()["in-mem-configs"]]
    assert "burst-cluster" in names
    # a huge job only the new cluster can hold
    uuid = submit(server, [{"command": "big", "mem": 8500, "cpus": 48}])["jobs"][0]
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    [inst] = server.store.job_instances(uuid)
    assert inst.compute_cluster == "burst-cluster"
    # duplicate creation rejected
    r = requests.post(f"{server.url}/compute-clusters", json={
        "kind": "mock", "name": "burst-cluster", "hosts": []},
        headers=hdr("admin"))
    assert r.status_code in (201, 400)


def test_malformed_json_is_400(server):
    r = requests.post(f"{server.url}/jobs", data="{bad", headers=hdr())
    assert r.status_code == 400
    assert "malformed" in r.json()["error"]


def test_swagger_endpoints(server):
    spec = requests.get(f"{server.url}/swagger-docs", headers=hdr()).json()
    assert spec["openapi"].startswith("3.")
    assert "/jobs" in spec["paths"]
    assert "post" in spec["paths"]["/jobs"]
    ui = requests.get(f"{server.url}/swagger-ui", headers=hdr())
    assert ui.status_code == 200 and "/jobs" in ui.text


def test_instance_stats_by_reason(server):
    uuid = submit(server, [{"command": "s", "mem": 100, "cpus": 1,
                            "max_retries": 1}])["jobs"][0]
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    [inst] = server.store.job_instances(uuid)
    server.clock.advance(5000)
    owner = server.scheduler.cluster_by_name(inst.compute_cluster)
    owner.fail_task(inst.task_id, "container-limitation-memory")
    stats = requests.get(f"{server.url}/stats/instances", headers=hdr()).json()
    assert stats["by-reason"].get("container-limitation-memory", 0) >= 1
    assert stats["by-status"].get("failed", 0) >= 1
    assert "percentiles" in stats["run-time-ms"]


def test_cors_allowlist(server):
    """CORS headers only for allowlisted origins — reflecting any Origin
    with Allow-Credentials lets arbitrary sites make credentialed
    cross-origin requests (advisor finding r1)."""
    evil = {"Origin": "https://evil.example", **hdr()}
    r = requests.get(f"{server.url}/info", headers=evil)
    assert "Access-Control-Allow-Origin" not in r.headers
    assert "Access-Control-Allow-Credentials" not in r.headers

    server.api.config.cors_origins = (
        "https://dashboard.example", r"re:https://.*\.corp\.example",
    )
    try:
        ok = {"Origin": "https://dashboard.example", **hdr()}
        r = requests.get(f"{server.url}/info", headers=ok)
        assert r.headers["Access-Control-Allow-Origin"] == \
            "https://dashboard.example"
        assert r.headers["Access-Control-Allow-Credentials"] == "true"
        regex_ok = {"Origin": "https://cook.corp.example", **hdr()}
        r = requests.get(f"{server.url}/info", headers=regex_ok)
        assert r.headers["Access-Control-Allow-Origin"] == \
            "https://cook.corp.example"
        r = requests.get(f"{server.url}/info", headers=evil)
        assert "Access-Control-Allow-Origin" not in r.headers
        # exact entries are never regex-interpreted: "." must not act as
        # a wildcard letting lookalike origins through
        lookalike = {"Origin": "https://dashboardxexample", **hdr()}
        r = requests.get(f"{server.url}/info", headers=lookalike)
        assert "Access-Control-Allow-Origin" not in r.headers
        # an invalid regex entry never matches and never 500s
        server.api.config.cors_origins = ("re:(unclosed",)
        r = requests.get(f"{server.url}/info", headers=evil)
        assert r.status_code == 200
        assert "Access-Control-Allow-Origin" not in r.headers
    finally:
        server.api.config.cors_origins = ()
