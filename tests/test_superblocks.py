"""Superblock (DCN-domain) layer of the hierarchical matcher
(ops/hierarchical.py `superblock_nodes`): packing parity vs the flat CPU
reference with the two-level coarse engaged, ONE XLA program per
(super-coarse, coarse, fine) bucket across superblock counts, the
stand-down rule below two superblocks, gang co-location at the FINE
block on the superblock path, and the scheduler/CycleRecord wiring
(`hier_superblock_nodes` -> hier_superblocks + super_coarse_solve
wall)."""
import numpy as np
import pytest

from cook_tpu.obs.compile_observatory import CompileObservatory
from cook_tpu.ops import cpu_reference as ref
from cook_tpu.ops.hierarchical import HierParams, hierarchical_match
from cook_tpu.parallel.mesh import make_mesh
from tests.test_hierarchical import (
    HIER_EFF_TOLERANCE,
    as_problem,
    assert_valid,
    dense_problem,
    efficiency,
)

# j=512 keeps the job axis at its own bucket, so the super-slot width
# bucket_size(2 * 512 / s_real) lands on 256 for every s_real in 4..7 —
# the lattice the one-program pin rides
SB_PARAMS = dict(nodes_per_block=32, superblock_nodes=64, chunk=64, kc=32)


@pytest.mark.parametrize("n", [320, 384, 448])
def test_superblock_parity_across_widths(n):
    """Packing parity vs the flat reference greedy with the DCN layer
    engaged: the extra routing level (super-coarse -> batched coarse ->
    fine) stays within HIER_EFF_TOLERANCE at several superblock counts
    of the same seeded shape family the classic-path tests pin."""
    demands, avail, totals = dense_problem(512, n, seed=n)
    problem = as_problem(demands, avail, totals)
    result, stats = hierarchical_match(
        problem, params=HierParams(**SB_PARAMS))
    a = np.asarray(result.assignment)
    assert_valid(demands, avail[:, :3], a)
    flat = ref.np_greedy_match(demands, avail[:, :3], totals)
    eff = efficiency(demands, a, flat)
    assert eff >= HIER_EFF_TOLERANCE, (n, eff)
    # geometry: sbn=64 (2 blocks of 32) -> n/64 superblocks
    assert stats["superblocks"] == n // 64
    assert stats["superblock_blocks"] == 2
    assert stats["superblock_nodes"] == 64
    assert stats["coarse_backend"] == "xla"  # forced on the batched path


def test_one_program_per_level_across_superblock_counts():
    """The mega-scale acceptance pin: three different REAL superblock
    counts (5, 6, 7 — none a power of two) pad onto the SAME
    (super-coarse, coarse, fine) shapes, so the CompileObservatory sees
    exactly ONE XLA program per level with the mesh engaged."""
    mesh = make_mesh()  # 8 virtual cpu devices (conftest)
    observatory = CompileObservatory()
    for n in (320, 384, 448):
        demands, avail, totals = dense_problem(512, n, seed=n)
        problem = as_problem(demands, avail, totals)
        result, stats = hierarchical_match(
            problem, params=HierParams(**SB_PARAMS),
            mesh=mesh, observatory=observatory)
        assert stats["superblocks"] == n // 64
        assert stats["super_shape"] == (512, 8)
        assert stats["coarse_shape"] == (8, 256, 2)
        assert stats["fine_shape"] == (16, 128, 32)
        a = np.asarray(result.assignment)
        assert_valid(demands, avail[:, :3], a)
        # zero phantom matches: every placement indexes a REAL node
        placed = a[a >= 0]
        assert (placed < n).all()
        assert (a >= 0).sum() > 0
    obs_stats = observatory.stats()
    assert obs_stats["match_super_coarse"]["programs"] == 1
    assert obs_stats["match_coarse"]["programs"] == 1
    assert obs_stats["match_fine"]["programs"] == 1


def test_superblock_layer_stands_down_below_two():
    """A pool spanning < 2 superblocks is a single DCN domain: the layer
    stands down and the solve is the classic two-level path (no
    super-coarse wall, no batched coarse shape)."""
    demands, avail, totals = dense_problem(256, 128, seed=1)
    problem = as_problem(demands, avail, totals)
    result, stats = hierarchical_match(
        problem, params=HierParams(nodes_per_block=32,
                                   superblock_nodes=256,  # > n -> 1 sb
                                   chunk=64, kc=32))
    assert stats["superblocks"] == 0
    assert stats["super_shape"] is None
    assert stats["super_coarse_s"] == 0.0
    assert len(stats["coarse_shape"]) == 2  # flat jobs x blocks
    assert_valid(demands, avail[:, :3], np.asarray(result.assignment))


def test_gang_lands_in_one_fine_block_on_superblock_path():
    """Gang co-location is pinned at the FINE block even with the DCN
    layer engaged: a gang landing in one superblock but two of its
    blocks would still be stripped — every placed gang's nodes share one
    nodes_per_block-aligned block, and no gang partially places."""
    rng = np.random.default_rng(11)
    j, n, npb = 128, 256, 32
    demands, avail, totals = dense_problem(j, n, seed=11)
    gang_id = np.full(j, -1, dtype=np.int32)
    gang_need = np.zeros(j, dtype=np.int32)
    # 8 gangs of 4 on the first 32 rows; the rest solo
    for g in range(8):
        rows = np.arange(g * 4, g * 4 + 4)
        gang_id[rows] = g
        gang_need[rows] = 4
    problem = as_problem(demands, avail, totals)
    result, stats = hierarchical_match(
        problem,
        params=HierParams(nodes_per_block=npb, superblock_nodes=64,
                          chunk=64, kc=32),
        gang_id=gang_id, gang_need=gang_need)
    assert stats["superblocks"] == n // 64
    a = np.asarray(result.assignment)
    assert_valid(demands, avail[:, :3], a)
    for g in range(8):
        rows = np.flatnonzero(gang_id == g)
        placed = a[rows]
        if (placed < 0).any():
            # all-or-nothing: a gang never partially places
            assert (placed < 0).all(), (g, placed)
            continue
        # distinct nodes, all inside ONE fine block
        assert len(set(placed.tolist())) == len(rows)
        assert len({int(p) // npb for p in placed}) == 1, (g, placed)
    assert stats["gangs"]["considered"] == 8
    assert stats["gangs"]["placed"] >= 1


# ------------------------------------------------------ scheduler wiring


def test_match_cycle_superblock_record():
    """MatchConfig.hierarchical_superblock_nodes threads through the
    matcher: the CycleRecord carries the superblock count and the
    super_coarse_solve wall joins the three classic hier_phases keys
    (and the record round-trips to JSON)."""
    from tests.test_hierarchical import _hier_config, _scenario

    config = _hier_config()
    # 64 hosts / 16 per block = 4 blocks; superblocks of 16 nodes round
    # up to 2 blocks (32 nodes) -> 2 DCN domains
    config.hierarchical_superblock_nodes = 16
    store, scheduler = _scenario(config)
    outcome = scheduler.match_cycle(store.pools["default"])
    assert len(outcome.matched) > 250
    record = scheduler.recorder.records(limit=1)[0]
    assert record.hierarchical
    assert record.hier_blocks == 4
    assert record.hier_superblocks == 2
    assert set(record.hier_phases) == {"super_coarse_solve",
                                       "coarse_solve", "fine_solve",
                                       "refine"}
    assert record.hier_phases["super_coarse_solve"] > 0
    as_json = record.to_json()
    assert as_json["hier_superblocks"] == 2


def test_superblocks_gauge_tracks_last_solve():
    """The `hierarchical.superblocks` gauge reports the DCN-domain count
    of the pool's last hierarchical solve (0 when the layer is off)."""
    from cook_tpu.utils.metrics import global_registry

    demands, avail, totals = dense_problem(256, 320, seed=2)
    problem = as_problem(demands, avail, totals)
    hierarchical_match(problem, params=HierParams(**SB_PARAMS),
                       pool="sb-pool")
    gauge = global_registry.gauge("hierarchical.superblocks")
    assert gauge.value(labels={"pool": "sb-pool"}) == 5
