"""Whole-OS-process leader failover over the networked lease service.

Three separate processes, nothing shared but TCP: a lease server
(control/lease_server.py — the ZooKeeper role), and two scheduler
processes (`python -m cook_tpu`) with SEPARATE data directories.  The
leader is SIGKILLed (no graceful release) and its data dir deleted; the
standby must take the lease after TTL expiry and serve the replicated
state — the reference's ZK-election + Datomic-replay failover
(mesos.clj:153-328, kubernetes/compute_cluster.clj:269) with no shared
filesystem anywhere.
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import time

import pytest
import requests

from cook_tpu.rest.server import free_port

H = {"X-Cook-Requesting-User": "u"}


def _wait(predicate, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            if predicate():
                return
        except requests.RequestException:
            pass
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")


def _write_config(tmp_path, name, port, data_dir, lease_url):
    config = {
        "port": port,
        "data_dir": data_dir,
        "leader_endpoint": lease_url,
        "leader_ttl_s": 2.0,
        "rank_interval_s": 0.5,
        "match_interval_s": 0.5,
        # control-plane-only nodes: a wedged accelerator (the site hook
        # force-registers one) must not stall the first rank cycle
        "platform": "cpu",
        "pools": [{"name": "default"}],
        "clusters": [{
            "kind": "mock", "name": "m1",
            "hosts": [{"node_id": "h0", "mem": 4000, "cpus": 8}],
        }],
    }
    path = tmp_path / f"{name}.json"
    path.write_text(json.dumps(config))
    return str(path)


def test_clamped_ttl_adopted_for_partition_grace():
    """The lease service clamps requested TTLs (MAX_TTL_S); the elector
    must grace-check partitions against the EFFECTIVE TTL the service
    reports back, not its configured ask — or a clamped lease leaves the
    old leader seated long after the service re-granted it (a two-leader
    window)."""
    from cook_tpu.control.leader import HttpLeaseElector
    from cook_tpu.control.lease_server import MAX_TTL_S, LeaseServer

    lease = LeaseServer().start()
    clock = {"t": 0.0}
    try:
        elector = HttpLeaseElector(
            lease.url, "g", "m1", ttl_s=300.0, timeout_s=1.0,
            clock=lambda: clock["t"])
        assert elector.try_acquire()
        assert elector.effective_ttl_s == MAX_TTL_S  # 60, not 300

        # partition the elector from the lease service
        elector.endpoint = "http://127.0.0.1:1"
        clock["t"] = MAX_TTL_S / 2
        assert elector.heartbeat(), \
            "partition within the granted TTL must not dethrone"
        clock["t"] = MAX_TTL_S + 40.0  # beyond granted 60, within asked 300
        assert not elector.heartbeat(), (
            "elector kept leading past the clamped TTL: the service may "
            "already have re-granted the lease")
    finally:
        lease.stop()


@pytest.mark.slow
def test_sigkill_leader_promotes_standby_no_shared_fs(tmp_path):
    lease_port = free_port()
    env = dict(os.environ)
    procs = []

    def spawn(*argv):
        p = subprocess.Popen([sys.executable, *argv], env=env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        procs.append(p)
        return p

    lease = spawn("-m", "cook_tpu.control.lease_server",
                  "--host", "127.0.0.1", "--port", str(lease_port))
    lease_url = f"http://127.0.0.1:{lease_port}"
    try:
        _wait(lambda: requests.get(f"{lease_url}/healthz",
                                   timeout=1).ok, 15, "lease server up")

        ports = [free_port(), free_port()]
        dirs = [str(tmp_path / "node1"), str(tmp_path / "node2")]
        nodes = []
        for i in (0, 1):
            cfg = _write_config(tmp_path, f"node{i}", ports[i], dirs[i],
                                lease_url)
            nodes.append(spawn("-m", "cook_tpu", "--config", cfg))
            # stagger so node0 deterministically wins the first election
            if i == 0:
                _wait(lambda: requests.get(
                    f"http://127.0.0.1:{ports[0]}/debug",
                    timeout=1).json()["leader"], 90, "node0 leads")

        leader_port, standby_port = ports
        leader_proc, standby_proc = nodes
        leader_dir = dirs[0]
        _wait(lambda: requests.get(
            f"http://127.0.0.1:{standby_port}/debug", timeout=1).ok,
            90, "standby REST up")

        uuid = "f0000000-0000-0000-0000-0000000000aa"
        r = requests.post(f"http://127.0.0.1:{leader_port}/jobs", json={
            "jobs": [{"command": "sleep 600", "mem": 100, "cpus": 1,
                      "uuid": uuid}]}, headers=H, timeout=5)
        assert r.status_code == 201, r.text

        # standby replicates the job into its OWN store/disk
        _wait(lambda: requests.get(
            f"http://127.0.0.1:{standby_port}/jobs/{uuid}",
            headers=H, timeout=2).status_code == 200,
            30, "standby replicated the job")

        # hard-kill the leader and burn its disk
        leader_proc.send_signal(signal.SIGKILL)
        leader_proc.wait(timeout=10)
        shutil.rmtree(leader_dir)

        _wait(lambda: requests.get(
            f"http://127.0.0.1:{standby_port}/debug",
            timeout=1).json()["leader"], 30, "standby promoted")
        # lease service agrees on the new leader's advertised URL
        current = requests.get(f"{lease_url}/leader?group=cook",
                               timeout=2).json()
        assert current["url"] == f"http://127.0.0.1:{standby_port}"

        # state survived: the job is there, and the NEW leader schedules
        # it to running on its mock cluster
        r = requests.get(f"http://127.0.0.1:{standby_port}/jobs/{uuid}",
                         headers=H, timeout=2)
        assert r.status_code == 200
        _wait(lambda: requests.get(
            f"http://127.0.0.1:{standby_port}/jobs/{uuid}",
            headers=H, timeout=2).json()["status"] == "running",
            30, "new leader schedules the replicated job")

        # and the new leader accepts writes directly
        uuid2 = "f0000000-0000-0000-0000-0000000000ab"
        r = requests.post(f"http://127.0.0.1:{standby_port}/jobs", json={
            "jobs": [{"command": "true", "mem": 50, "cpus": 1,
                      "uuid": uuid2}]}, headers=H, timeout=5)
        assert r.status_code == 201, r.text
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
