"""Per-shard journal segments: layout, recovery, migration, replay.

The durability contract per shard: each segment (snapshot + journal
suffix) reconstructs ITS shard exactly, cross-shard moves replay from
the two segments independently, and the single-journal -> sharded
migration is exactly-once and lossless.
"""
import os

import pytest

from cook_tpu.models import persistence
from cook_tpu.models.entities import InstanceStatus, Job, Pool, Resources
from cook_tpu.models.store import JobStore
from cook_tpu.shard import ShardedStore, ShardedTransactionLog, ShardRouter
from cook_tpu.shard import journal as shard_journal


def job(uuid, pool, user="u0"):
    return Job(uuid=uuid, user=user, pool=pool, command="true",
               resources=Resources(mem=64, cpus=1))


def build_plane(tmp_path, n_shards=4):
    store = ShardedStore(n_shards)
    pools = store.router.pools_for_distinct_shards()
    journals = shard_journal.attach_shard_journals(store, str(tmp_path))
    for name in pools:
        store.set_pool(Pool(name=name))
    txn = ShardedTransactionLog(store, journals=journals)
    return store, txn, journals, pools


def test_sharded_recovery_replays_each_segment(tmp_path):
    store, txn, journals, pools = build_plane(tmp_path)
    for i in range(8):
        txn.commit("jobs/submit", {"jobs": [job(f"d{i}", pools[i % 4])]})
    store.create_instance("d0", "t0", hostname="h0")
    store.update_instance_state("t0", InstanceStatus.SUCCESS)
    for journal in journals:
        journal.close()
    recovered = shard_journal.recover_sharded(str(tmp_path), 4)
    assert recovered is not None
    assert len(recovered.jobs) == 8
    assert recovered.jobs["d3"].pool == pools[3]
    assert recovered.job_instances("d0")[0].status is \
        InstanceStatus.SUCCESS
    # per-shard sequence numbering survives (replication resumes from
    # each shard's own head)
    assert recovered.last_seqs() == store.last_seqs()
    # idempotency tables recovered per shard: a replayed commit dedupes
    outcome = ShardedTransactionLog(recovered).commit(
        "jobs/submit", {"jobs": [job("d0", pools[0])]},
        txn_id=next(iter(store.shards[0].txn_results)))
    assert outcome.duplicate


def test_cross_shard_move_survives_per_segment_replay(tmp_path):
    store, txn, journals, pools = build_plane(tmp_path)
    txn.commit("jobs/submit", {"jobs": [job("mv", pools[0])]})
    txn.commit("job/pool-move", {"uuid": "mv", "pool": pools[3]})
    for journal in journals:
        journal.close()
    recovered = shard_journal.recover_sharded(str(tmp_path), 4)
    router = recovered.router
    src = recovered.shards[router.shard_for_pool(pools[0])]
    dst = recovered.shards[router.shard_for_pool(pools[3])]
    # the source segment's shard-out replayed (no duplicate ownership)
    assert "mv" not in src.jobs
    assert dst.jobs["mv"].pool == pools[3]
    assert len(recovered.jobs) == 1


def test_snapshot_sharded_plus_suffix(tmp_path):
    store, txn, journals, pools = build_plane(tmp_path)
    txn.commit("jobs/submit", {"jobs": [job("pre", pools[1])]})
    shard_journal.snapshot_sharded(store, str(tmp_path))
    for journal in journals:
        journal.rotate()
    txn.commit("jobs/submit", {"jobs": [job("post", pools[1])]})
    for journal in journals:
        journal.close()
    recovered = shard_journal.recover_sharded(str(tmp_path), 4)
    assert set(recovered.jobs.keys()) == {"pre", "post"}


def test_recover_uses_on_disk_shard_count(tmp_path):
    store, txn, journals, pools = build_plane(tmp_path, n_shards=4)
    txn.commit("jobs/submit", {"jobs": [job("a", pools[0])]})
    for journal in journals:
        journal.close()
    # a misconfigured node asking for 8 shards still recovers the
    # 4-shard layout (resharding is a migration, not a config edit)
    recovered = shard_journal.recover_sharded(str(tmp_path), 8)
    assert recovered.n_shards == 4
    assert "a" in recovered.jobs


# ---------------------------------------------------------------- migration


def make_single_layout(tmp_path, n_jobs=10):
    store = JobStore()
    journal = persistence.attach_journal(
        store, os.path.join(str(tmp_path), "journal.jsonl"))
    pools = ShardRouter(4).pools_for_distinct_shards()
    for name in pools:
        store.set_pool(Pool(name=name))
    store.submit_jobs([job(f"m{i:02d}", pools[i % 4])
                       for i in range(n_jobs)])
    store.create_instance("m00", "mt0", hostname="h0")
    store.note_txn("txn-old", "jobs/submit", {"jobs": ["m00"]})
    journal.close()
    return store, pools


def test_migration_round_trip_and_idempotence(tmp_path):
    source, pools = make_single_layout(tmp_path)
    first = shard_journal.migrate_single_journal(str(tmp_path), 4)
    assert first["migrated"] and first["jobs"] == 10
    assert sum(first["per_shard_jobs"]) == 10
    # exactly-once: the manifest marks the dir sharded
    again = shard_journal.migrate_single_journal(str(tmp_path), 4)
    assert not again["migrated"]
    assert again["reason"] == "already-sharded"
    # originals renamed, never replayed by an unsharded recover
    assert os.path.exists(
        os.path.join(str(tmp_path), "journal.jsonl.premigrate"))
    assert persistence.recover(str(tmp_path)) is None
    recovered = shard_journal.recover_sharded(str(tmp_path), 4)
    assert set(recovered.jobs.keys()) == set(source.jobs.keys())
    assert recovered.jobs["m03"].pool == source.jobs["m03"].pool
    assert recovered.job_instances("m00")[0].task_id == "mt0"
    assert set(recovered.pools) == set(pools)
    # submission-order tie-break survives per shard
    shard = recovered.shard_of_job("m00")
    same_shard = sorted((u for u in source.jobs
                         if shard.jobs.get(u) is not None),
                        key=lambda u: source.job_seq[u])
    assert sorted(shard.job_seq, key=lambda u: shard.job_seq[u]) == \
        same_shard
    # the idempotency table migrated to every shard
    txn = ShardedTransactionLog(recovered)
    assert txn.commit("jobs/submit", {"jobs": [job("m00", pools[0])]},
                      txn_id="txn-old").duplicate


def test_migration_of_fresh_dir_stamps_manifest(tmp_path):
    outcome = shard_journal.migrate_single_journal(str(tmp_path), 4)
    assert outcome["reason"] == "fresh"
    manifest = shard_journal.read_manifest(str(tmp_path))
    assert manifest["shards"] == 4
    assert not shard_journal.has_single_journal_layout(str(tmp_path))


def test_migration_rejects_single_shard_target(tmp_path):
    with pytest.raises(ValueError):
        shard_journal.migrate_single_journal(str(tmp_path), 1)
