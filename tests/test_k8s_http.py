"""HttpKubeApi against a live (fake) HTTP apiserver: pod CRUD + manifest
construction, watch event flow, re-list on 410 gap, token refresh —
KubeCluster runs UNMODIFIED against the HTTP client (the round-1 gap:
kubernetes/api.clj:449-905,2152 had no analog)."""
import os
import time

import pytest

from cook_tpu.cluster.base import TaskSpec
from cook_tpu.cluster.k8s import KubeCluster, PodPhase
from cook_tpu.cluster.k8s_http import (
    COOK_MANAGED_LABEL,
    HttpKubeApi,
    parse_cpu,
    parse_mem,
)
from tests.fake_apiserver import make_server


def wait_for(predicate, timeout=5.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture()
def apiserver():
    server, state, url = make_server()
    yield state, url
    server.shutdown()


@pytest.fixture()
def api(apiserver):
    state, url = apiserver
    api = HttpKubeApi(url, namespace="default", watch_timeout_s=5.0,
                      relist_backoff_s=0.05)
    yield api
    api.stop()


def spec(task_id="t1", node="n1", mem=512.0, cpus=2.0):
    return TaskSpec(task_id=task_id, job_uuid="j1", user="alice",
                    command="echo hi", mem=mem, cpus=cpus, gpus=0.0,
                    node_id=node, hostname=node,
                    env=(("FOO", "bar"),), container_image="img:1")


def test_quantity_parsing():
    assert parse_mem("512Mi") == 512.0
    assert parse_mem("2Gi") == 2048.0
    # unsuffixed memory is BYTES (the apiserver's normalized form)
    assert parse_mem("1073741824") == 1024.0
    assert parse_mem("1G") == pytest.approx(1e9 / (1024 * 1024))
    assert parse_mem("1Pi") == 1024.0**3
    assert parse_cpu("500m") == 0.5
    assert parse_cpu("4") == 4.0


def test_list_nodes_and_manifest_roundtrip(apiserver, api):
    state, _ = apiserver
    state.add_node("n1", 8192, 16, labels={"cook.scheduler/pool": "default",
                                           "rack": "r1"})
    [node] = api.list_nodes()
    assert node.name == "n1" and node.mem == 8192 and node.cpus == 16
    assert node.schedulable and dict(node.labels)["rack"] == "r1"


def test_launch_builds_full_pod_manifest(apiserver, api):
    state, _ = apiserver
    state.add_node("n1", 8192, 16)
    clock = lambda: 0
    cluster = KubeCluster("k", api, clock)
    cluster.launch_tasks("default", [spec()])
    manifest = state.pods["t1"]
    assert manifest["spec"]["nodeName"] == "n1"
    [main] = [c for c in manifest["spec"]["containers"]
              if c["name"] == "cook-job"]
    assert main["image"] == "img:1"
    assert main["command"] == ["/bin/sh", "-c", "echo hi"]
    assert {"name": "FOO", "value": "bar"} in main["env"]
    assert main["resources"]["requests"]["memory"] == "512Mi"
    assert main["resources"]["requests"]["cpu"] == "2.0"
    assert manifest["metadata"]["labels"][COOK_MANAGED_LABEL] == "true"


def test_checkpoint_pod_wiring(apiserver):
    """A checkpointing job's pod gets the tools volume, init container,
    and the mount (api.clj:934,1173-1198); checkpoint env and the memory
    overhead arrive already folded into the TaskSpec by the matcher."""
    state, url = apiserver
    state.add_node("n1", 8192, 16)
    api = HttpKubeApi(url, checkpoint_tools_image="ckpt-tools:1")
    cluster = KubeCluster("k", api, lambda: 0)
    import dataclasses

    task = dataclasses.replace(spec(), checkpoint_mode="auto",
                               checkpoint_periodic_sec=300)
    cluster.launch_tasks("default", [task])
    manifest = state.pods["t1"]
    [init] = manifest["spec"]["initContainers"]
    assert init["name"] == "aux-cook-init-container-for-checkpoint"
    assert init["image"] == "ckpt-tools:1"
    [volume] = manifest["spec"]["volumes"]
    assert volume["name"] == "cook-checkpoint-tools"
    [main] = [c for c in manifest["spec"]["containers"]
              if c["name"] == "cook-job"]
    assert main["volumeMounts"][0]["mountPath"] == "/opt/cook-checkpoint"
    # the spec's mem is used verbatim (overhead was added at match time)
    assert main["resources"]["requests"]["memory"] == "512Mi"


def test_watch_drives_controller_to_success(apiserver, api):
    state, _ = apiserver
    state.add_node("n1", 8192, 16)
    clock = lambda: 0
    cluster = KubeCluster("k", api, clock)
    statuses = []
    cluster.status_callback = lambda tid, st, reason: statuses.append(
        (tid, st.value, reason))
    api.start()
    cluster.launch_tasks("default", [spec()])
    wait_for(lambda: "t1" in state.pods, what="pod created")
    state.set_phase("t1", "Running")
    wait_for(lambda: ("t1", "running", None) in statuses,
             what="running status")
    state.set_phase("t1", "Succeeded")
    wait_for(lambda: ("t1", "success", "normal-exit") in statuses,
             what="success status")
    # the controller garbage-collects the completed pod via the api
    wait_for(lambda: "t1" not in state.pods, what="pod deleted")


def test_watch_gap_recovers_via_relist(apiserver, api):
    """Events missed during a watch gap are reconstructed from a fresh
    LIST diff (the api.clj:449 re-list branch): a pod that completed
    while the watch was down still reaches the controller."""
    state, _ = apiserver
    state.add_node("n1", 8192, 16)
    clock = lambda: 0
    cluster = KubeCluster("k", api, clock)
    statuses = []
    cluster.status_callback = lambda tid, st, reason: statuses.append(
        (tid, st.value))
    api.start()
    cluster.launch_tasks("default", [spec()])
    state.set_phase("t1", "Running")
    wait_for(lambda: ("t1", "running") in statuses, what="running")
    # compact history + sever the stream, then mutate during the outage
    state.inject_gap()
    state.set_phase("t1", "Succeeded")
    wait_for(lambda: ("t1", "success") in statuses, timeout=10,
             what="success via re-list after 410")


def test_pod_deleted_externally_is_mea_culpa(apiserver, api):
    state, _ = apiserver
    state.add_node("n1", 8192, 16)
    cluster = KubeCluster("k", api, lambda: 0)
    statuses = []
    cluster.status_callback = lambda tid, st, reason: statuses.append(
        (tid, st.value, reason))
    api.start()
    cluster.launch_tasks("default", [spec()])
    state.set_phase("t1", "Running")
    wait_for(lambda: ("t1", "running", None) in statuses, what="running")
    state.delete_pod("t1")  # node drained / manual kubectl delete
    wait_for(
        lambda: ("t1", "failed", "could-not-reconstruct-state") in statuses,
        what="mea-culpa failure")


def test_bearer_token_refresh(apiserver, tmp_path):
    state, url = apiserver
    token_file = tmp_path / "token"
    token_file.write_text("token-one")
    api = HttpKubeApi(url, token_file=str(token_file))
    api.list_nodes()
    assert state.auth_headers[-1] == "Bearer token-one"
    token_file.write_text("token-two")
    # force an mtime change even on coarse-grained filesystems
    os.utime(token_file, (time.time() + 2, time.time() + 2))
    api.list_nodes()
    assert state.auth_headers[-1] == "Bearer token-two"


def test_synthesized_offers_over_http(apiserver, api):
    state, _ = apiserver
    state.add_node("n1", 8192, 16)
    cluster = KubeCluster("k", api, lambda: 0)
    cluster.launch_tasks("default", [spec(mem=2048, cpus=4)])
    [offer] = cluster.pending_offers("default")
    assert offer.mem == 8192 - 2048
    assert offer.cpus == 16 - 4


def test_full_process_schedules_onto_http_apiserver(apiserver):
    """The whole service (build_process with a `k8s-http` cluster) places a
    submitted job as a pod on the fake apiserver and completes it from
    watch events — no FakeKubeApi anywhere in the path."""
    from cook_tpu.components import (
        build_process,
        shutdown,
        start_leader_duties,
    )
    from cook_tpu.models.entities import JobState
    from cook_tpu.utils.config import Settings

    state, url = apiserver
    state.add_node("n1", 8192, 16)
    settings = Settings(
        rank_interval_s=3600, match_interval_s=3600,
        clusters=[{"kind": "k8s-http", "name": "kprod", "url": url,
                   "watch_timeout_s": 5}],
    )
    process = build_process(settings, start_rest=False)
    try:
        start_leader_duties(process, block=False, on_loss=lambda: None)
        from tests.conftest import make_job

        job = make_job(mem=512, cpus=2)
        process.store.submit_jobs([job])
        loops = {l.name: l for l in process.loops}
        loops["rank"].fire()
        loops["match"].fire()
        wait_for(lambda: len(state.pods) == 1, what="pod on apiserver")
        [name] = state.pods
        state.set_phase(name, "Running")
        wait_for(lambda: process.store.jobs[job.uuid].state
                 == JobState.RUNNING, what="job running")
        state.set_phase(name, "Succeeded")
        wait_for(lambda: process.store.jobs[job.uuid].state
                 == JobState.COMPLETED, what="job completed")
    finally:
        shutdown(process)
        for cluster in process.clusters:
            cluster.api.stop()


def test_relist_prunes_stale_local_view(apiserver, api):
    """A pod deleted during the gap disappears from the client's view and
    the controller observes the deletion."""
    state, _ = apiserver
    state.add_node("n1", 8192, 16)
    events = []
    api.set_pod_watch(lambda name, pod: events.append(
        (name, None if pod is None else pod.phase)))
    api.start()
    state.create_pod(api.pod_manifest(
        __import__("cook_tpu.cluster.k8s", fromlist=["KubePod"]).KubePod(
            name="p1", node_name="n1", mem=100, cpus=1)))
    wait_for(lambda: ("p1", PodPhase.PENDING) in events, what="added")
    state.inject_gap()
    state.delete_pod("p1")
    wait_for(lambda: ("p1", None) in events, timeout=10,
             what="deletion via re-list")
