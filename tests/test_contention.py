"""GET /debug/contention and the control-plane degradation reasons:
each serialization point's instrument, and the /debug/health transitions
they drive (store-lock-saturation, fsync-stall, replication-lag,
commit-ack-slo-burn, job-starvation) — the inducing-test pattern of
tests/test_health_endpoint.py, control-plane edition.

The server here runs WITHOUT a scheduler on purpose: the contention
observatory must work on proxy-only nodes (device telemetry reports
"unobserved" while the control-plane checks still run)."""
import threading
import time

import pytest
import requests

from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.obs.contention import (
    ContentionParams,
    JournalTelemetry,
    SloBurnTracker,
)
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread
from tests.conftest import FakeClock, make_job

PARAMS = ContentionParams(
    lock_contention_ratio=0.4,
    lock_min_acquisitions=32,
    fsync_stall_s=0.05,
    replication_lag_events=10,
    replication_ack_age_s=5.0,
    commit_ack_slo_s=0.5,
    starvation_age_s=60.0,
)


@pytest.fixture(scope="module")
def server():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    api = CookApi(store, None,
                  ApiConfig(admins=("admin",), contention=PARAMS))
    srv = ServerThread(api).start()
    srv.clock = clock
    srv.store = store
    srv.cook_api = api
    yield srv
    srv.stop()


def hdr(user="alice"):
    return {"X-Cook-Requesting-User": user}


def get_health(server):
    r = requests.get(f"{server.url}/debug/health")
    assert r.status_code == 200
    return r.json()


def get_contention(server):
    r = requests.get(f"{server.url}/debug/contention", headers=hdr())
    assert r.status_code == 200
    return r.json()


def kill_all_pending(server):
    pending = [j.uuid for j in server.store.pending_jobs("default")]
    if pending:
        server.store.kill_jobs(pending)


# --------------------------------------------------------------- snapshot


def test_contention_endpoint_sections(server):
    """Real REST traffic shows up in every section of the snapshot."""
    r = requests.post(
        f"{server.url}/jobs",
        json={"jobs": [{"command": "true", "mem": 64, "cpus": 0.5}]},
        headers=hdr())
    assert r.status_code == 201
    snap = get_contention(server)
    assert set(snap) >= {"store_lock", "journal", "replication",
                         "endpoints", "commit_ack", "starvation",
                         "wall_time"}
    lock = snap["store_lock"]
    assert lock["acquisitions"] > 0
    # per-call-site attribution: the submit path's store sites are named
    assert any(site.startswith("store.") for site in lock["sites"])
    post_jobs = snap["endpoints"]["POST /jobs"]
    assert post_jobs["count"] >= 1 and post_jobs["p50_ms"] > 0
    assert snap["commit_ack"]["slow_samples"] >= 1
    assert snap["commit_ack"]["p50_ms"] > 0
    assert "default" in snap["starvation"]


def test_unobserved_device_side_still_reports_contention(server):
    """No scheduler attached: device telemetry is 'unobserved', but the
    contention checks run and report evidence."""
    health = get_health(server)
    assert health["status"] in ("unobserved", "degraded")
    assert set(health["checks"]["contention"]) == {
        "store_lock", "journal", "replication", "commit_ack",
        "starvation"}


def test_rest_and_lock_metrics_exposed(server):
    requests.get(f"{server.url}/pools", headers=hdr())
    requests.get(f"{server.url}/nope", headers=hdr())  # unmatched: safe
    text = requests.get(f"{server.url}/metrics").text
    assert "cook_rest_request_seconds_bucket" in text
    assert 'route="/pools"' in text
    assert "cook_store_lock_wait_seconds_bucket" in text
    assert "cook_store_lock_hold_seconds_bucket" in text
    snap = get_contention(server)
    assert "GET __unmatched__" in snap["endpoints"]


# ------------------------------------------------- store-lock-saturation


def induce_lock_contention(store, rounds=8, waiters=50):
    """Hold the store lock while a batch of threads parks on it: every
    waiter records a contended outermost acquisition."""
    for _ in range(rounds):
        with store._lock:
            threads = [threading.Thread(
                target=lambda: store.pending_count("default"))
                for _ in range(waiters)]
            for t in threads:
                t.start()
            time.sleep(0.02)  # let them park on the lock
        for t in threads:
            t.join()


def test_store_lock_saturation_transition(server):
    induce_lock_contention(server.store)
    profiler = server.store._lock.profiler
    assert profiler.contention_ratio() >= PARAMS.lock_contention_ratio
    health = get_health(server)
    assert not health["healthy"]
    assert "store-lock-saturation" in health["reasons"]
    [degradation] = [d for d in health["degradations"]
                     if d["reason"] == "store-lock-saturation"]
    assert degradation["contention_ratio"] >= PARAMS.lock_contention_ratio
    # recovery: a clean window of uncontended acquisitions
    for _ in range(600):
        with server.store._lock:
            pass
    assert get_health(server)["healthy"]


# ------------------------------------------------------------ fsync-stall


def test_fsync_stall_transition(server):
    observatory = server.cook_api.contention
    old = observatory.journal_fn
    telemetry = JournalTelemetry()
    observatory.journal_fn = lambda: telemetry
    try:
        telemetry.note_fsync(4, 0.2)  # 200 ms >> the 50 ms bound
        health = get_health(server)
        assert "fsync-stall" in health["reasons"]
        [d] = [d for d in health["degradations"]
               if d["reason"] == "fsync-stall"]
        assert d["recent_fsync_max_s"] == pytest.approx(0.2)
        # recovery: the stall ages out of the recent-fsync window
        for _ in range(64):
            telemetry.note_fsync(1, 0.0005)
        assert get_health(server)["healthy"]
    finally:
        observatory.journal_fn = old


def test_journal_writer_reports_into_telemetry(tmp_path):
    """The real write path feeds the writer's telemetry: append + group
    fsync land in the counters the snapshot serves."""
    from cook_tpu.models import persistence

    writer = persistence.JournalWriter(str(tmp_path / "j.jsonl"))
    writer.write_line('{"seq": 1, "kind": "test"}')
    writer.write_line('{"seq": 2, "kind": "test"}')
    writer.sync()
    after = writer.telemetry.snapshot()
    assert after["appends"] == 2
    assert after["bytes_written"] > 0
    assert after["fsyncs"] == 1
    assert after["last_batch_events"] == 2  # one barrier covered both
    # rotation drops the unfsynced tail with the old file: the next
    # fsync's batch covers only post-rotate appends, no phantom carry
    writer.write_line('{"seq": 3, "kind": "test"}')
    writer.rotate()
    writer.write_line('{"seq": 4, "kind": "test"}')
    writer.sync()
    assert writer.telemetry.snapshot()["last_batch_events"] == 1
    writer.close()


# -------------------------------------------------------- replication-lag


def test_replication_lag_transition(server):
    api = server.cook_api
    api.replication_ack_meta["standby-1"] = {
        "seq": server.store.last_seq(), "durable": True,
        "time": time.monotonic(), "last_txn_id": ""}
    try:
        assert get_health(server)["healthy"]
        # the leader commits 12 more events; the follower's ack stands
        server.store.submit_jobs([make_job() for _ in range(12)])
        health = get_health(server)
        assert "replication-lag" in health["reasons"]
        [d] = [d for d in health["degradations"]
               if d["reason"] == "replication-lag"]
        assert d["follower"] == "standby-1"
        assert d["lag_events"] >= PARAMS.replication_lag_events
        assert d["durable"] is True
        # the leader-side gauges track the lag
        snap = get_contention(server)
        [row] = snap["replication"]
        assert row["lag_events"] >= 12
        # recovery: the follower catches up
        api.replication_ack_meta["standby-1"]["seq"] = \
            server.store.last_seq()
        assert get_health(server)["healthy"]
    finally:
        api.replication_ack_meta.pop("standby-1", None)
        kill_all_pending(server)


def test_silent_behind_follower_degrades(server):
    """A follower only 1 event behind but silent past the ack-age bound
    is a lag too: sync-ack commits are timing out against it."""
    api = server.cook_api
    api.replication_ack_meta["standby-2"] = {
        "seq": server.store.last_seq(), "durable": True,
        "time": time.monotonic() - 30.0, "last_txn_id": ""}
    try:
        server.store.submit_jobs([make_job()])
        health = get_health(server)
        assert "replication-lag" in health["reasons"]
    finally:
        api.replication_ack_meta.pop("standby-2", None)
        kill_all_pending(server)
    assert get_health(server)["healthy"]


# --------------------------------------------------- commit-ack-slo-burn


def test_commit_ack_burn_transition(server):
    observatory = server.cook_api.contention
    old = observatory.commit_ack
    observatory.commit_ack = SloBurnTracker()
    try:
        for _ in range(20):
            observatory.commit_ack.observe(2.0)   # 2 s >> 0.5 s SLO
        health = get_health(server)
        assert "commit-ack-slo-burn" in health["reasons"]
        [d] = [d for d in health["degradations"]
               if d["reason"] == "commit-ack-slo-burn"]
        assert d["fast_burn"] > 1.0 and d["slow_burn"] > 1.0
        # recovery: burn is a violating FRACTION — a flood of in-SLO
        # samples dilutes the burst below the budget in both windows
        for _ in range(4096):
            observatory.commit_ack.observe(0.001)
        assert get_health(server)["healthy"]
    finally:
        observatory.commit_ack = old


def test_burn_requires_both_windows():
    """A blip trips the fast window only; the multi-window rule keeps it
    from paging."""
    tracker = SloBurnTracker()
    now = time.time()
    # old, in-SLO history fills the slow window
    for i in range(400):
        tracker.observe(0.01, t=now - 2000 + i)
    # a recent blip: 3 slow samples among 10 fast
    for i in range(10):
        tracker.observe(0.01, t=now - 5 + i * 0.1)
    for i in range(3):
        tracker.observe(2.0, t=now - 1 + i * 0.1)
    stats = tracker.stats(threshold_s=0.5, budget=0.01, fast_s=300.0,
                          slow_s=3600.0, now=now)
    assert stats["fast_burn"] > 1.0
    assert stats["slow_burn"] < 1.0  # diluted by the healthy history


def test_slow_window_honest_past_ring_capacity():
    """Burn counts come from time buckets, not the percentile ring: a
    commit rate high enough to overflow the ring must not shrink the
    slow window onto the fast window's samples (which would page on
    exactly the blip the multi-window rule exists to suppress)."""
    tracker = SloBurnTracker(capacity=256)
    now = time.time()
    # 2000 in-SLO samples spread over ~33 min — 8x the ring capacity
    for i in range(2000):
        tracker.observe(0.01, t=now - 2000 + i)
    # a 20 s blip of violations at the end
    for i in range(40):
        tracker.observe(2.0, t=now - 20 + i * 0.5)
    stats = tracker.stats(threshold_s=0.5, budget=0.01, fast_s=300.0,
                          slow_s=3600.0, now=now)
    assert stats["slow_samples"] == 2040     # all counted, ring is 256
    assert stats["fast_burn"] > 1.0
    assert stats["slow_burn"] > 1.0          # 40/2040 = 2% of a 1% budget
    # the same blip against a full hour of healthy history stays quiet
    tracker2 = SloBurnTracker(capacity=256)
    for i in range(3500):
        tracker2.observe(0.01, t=now - 3500 + i)
    for i in range(40):
        tracker2.observe(2.0, t=now - 20 + i * 0.5)
    stats2 = tracker2.stats(threshold_s=0.5, budget=0.01, fast_s=300.0,
                            slow_s=3600.0, now=now)
    assert stats2["fast_burn"] > 1.0
    assert stats2["slow_burn"] > 1.0  # 40/3540 still > 1% budget
    # dilute below budget: violations under 1% of the slow window
    tracker3 = SloBurnTracker(capacity=256)
    for i in range(3500):
        tracker3.observe(0.01, t=now - 3500 + i)
        tracker3.observe(0.01, t=now - 3500 + i + 0.5)
    for i in range(40):
        tracker3.observe(2.0, t=now - 20 + i * 0.5)
    stats3 = tracker3.stats(threshold_s=0.5, budget=0.01, fast_s=300.0,
                            slow_s=3600.0, now=now)
    assert stats3["fast_burn"] > 1.0
    assert stats3["slow_burn"] < 1.0  # 40/7040 < 1% budget: blip only


def test_endpoint_rps_not_capped_by_sample_window():
    """A route busier than maxlen/window_s must report its true rate:
    the divisor is the retained history span, not the nominal window."""
    from cook_tpu.obs.contention import EndpointTelemetry

    t = EndpointTelemetry(samples_per_route=64)
    for _ in range(64):
        t.begin("/jobs", "POST")
        t.done("/jobs", "POST", 201, 0.002)
    snap = t.snapshot(window_s=60.0)
    row = snap["POST /jobs"]
    # 64 requests landed in well under a second; a 60 s divisor would
    # report ~1 rps
    assert row["rps"] > 60.0


def test_job_starvation_transition(server):
    kill_all_pending(server)
    job = make_job(user="starved-user")
    server.store.submit_jobs([job])
    assert get_health(server)["healthy"]  # just queued
    server.clock.advance(120_000)         # 120 s > the 60 s bound
    health = get_health(server)
    assert "job-starvation" in health["reasons"]
    [d] = [d for d in health["degradations"]
           if d["reason"] == "job-starvation"]
    assert d["pool"] == "default"
    assert d["oldest_age_s"] == pytest.approx(120.0)
    assert d["oldest_job"] == job.uuid
    assert d["worst_user"] == "starved-user"
    # the /unscheduled_jobs echo carries the same view
    r = requests.get(f"{server.url}/unscheduled_jobs",
                     params={"job": job.uuid}, headers=hdr())
    [entry] = r.json()
    assert entry["starvation"]["job_wait_s"] == pytest.approx(120.0)
    assert entry["starvation"]["pool_oldest_wait_s"] == \
        pytest.approx(120.0)
    assert entry["starvation"]["pool_worst_user"] == "starved-user"
    # recovery: the job leaves the queue
    server.store.kill_jobs([job.uuid])
    assert get_health(server)["healthy"]


def test_starvation_gauges(store, clock):
    from cook_tpu.scheduler.monitor import collect_pool_stats, \
        starvation_stats
    from cook_tpu.utils.metrics import global_registry

    store.submit_jobs([make_job(user="u1"), make_job(user="u2")])
    clock.advance(45_000)
    store.submit_jobs([make_job(user="u2")])
    sv = starvation_stats(store, "default")
    assert sv["oldest_age_s"] == pytest.approx(45.0)
    assert sv["user_max_wait_s"]["u1"] == pytest.approx(45.0)
    assert sv["user_max_wait_s"]["u2"] == pytest.approx(45.0)
    assert sv["worst_user_wait_s"] == pytest.approx(45.0)
    collect_pool_stats(store, "default")
    g = global_registry.gauge
    assert g("monitor.oldest_waiting_age_seconds").value(
        {"pool": "default"}) == pytest.approx(45.0)
    assert g("monitor.user_max_wait_seconds").value(
        {"pool": "default", "user": "u1"}) == pytest.approx(45.0)


def test_user_wait_gauge_retracted_when_user_stops_waiting(store, clock):
    """A scheduled (or killed) user's max-wait gauge must disappear, not
    freeze at its last value — a frozen 900 s reads as live starvation
    forever, and user labels would accumulate with workload churn."""
    from cook_tpu.scheduler.monitor import collect_pool_stats
    from cook_tpu.utils.metrics import global_registry

    jobs = [make_job(user="transient"), make_job(user="sticky")]
    store.submit_jobs(jobs)
    clock.advance(30_000)
    collect_pool_stats(store, "default")
    gauge = global_registry.gauge("monitor.user_max_wait_seconds")
    labels = {"pool": "default", "user": "transient"}
    assert gauge.value(labels) == pytest.approx(30.0)
    store.kill_jobs([jobs[0].uuid])
    collect_pool_stats(store, "default")
    assert gauge.value(labels) == 0.0
    assert gauge.value({"pool": "default",
                        "user": "sticky"}) == pytest.approx(30.0)


# ------------------------------------------------------------- profiling


def test_reentrant_acquisitions_not_double_counted(store):
    """store.submit_jobs holds the lock and calls locked helpers; only
    the outermost acquisition may count (re-entrant waits are zero by
    construction and would dilute the contention ratio)."""
    profiler = store._lock.profiler
    before = profiler.acquisitions
    with store._lock:
        with store._lock:       # re-entrant: passes straight through
            store.pending_count("default")
    assert profiler.acquisitions == before + 1


def test_lock_profiler_attributes_call_sites(store):
    store.submit_jobs([make_job()])
    snap = store._lock.profiler.snapshot()
    assert "store.submit_jobs" in snap["sites"]
    site = snap["sites"]["store.submit_jobs"]
    assert site["acquisitions"] >= 1
    assert site["hold_s"] > 0
