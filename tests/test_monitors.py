"""Aux subsystem tests: progress aggregation, heartbeats, monitor gauges,
sandbox publishing, autoscale wiring, lingering/straggler killers."""
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import (
    DEFAULT_USER,
    Group,
    InstanceStatus,
    JobState,
    Pool,
    Resources,
    Share,
    StragglerHandling,
)
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler
from cook_tpu.scheduler.heartbeat import HeartbeatMonitor
from cook_tpu.scheduler.monitor import collect_pool_stats
from cook_tpu.scheduler.progress import ProgressAggregator, ProgressUpdate
from cook_tpu.scheduler.sandbox import SandboxPublisher
from tests.conftest import FakeClock, make_job


def setup(n_hosts=2, cpus=8.0):
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock",
        [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4000, cpus=cpus)
         for i in range(n_hosts)],
        clock=clock,
    )
    scheduler = Scheduler(store, [cluster])
    return clock, store, cluster, scheduler


def run_job(store, scheduler, job):
    store.submit_jobs([job])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    return store.job_instances(job.uuid)[-1]


class TestProgress:
    def test_newest_sequence_wins_and_batch_publish(self):
        clock, store, cluster, scheduler = setup()
        inst = run_job(store, scheduler, make_job())
        agg = ProgressAggregator(store)
        assert agg.handle(ProgressUpdate(inst.task_id, 2, 40, "later"))
        assert not agg.handle(ProgressUpdate(inst.task_id, 1, 99, "stale"))
        assert agg.publish() == 1
        assert store.instances[inst.task_id].progress == 40

    def test_pending_cap_drops(self):
        clock, store, *_ = setup()
        agg = ProgressAggregator(store, max_pending=2)
        assert agg.handle(ProgressUpdate("a", 1, 1))
        assert agg.handle(ProgressUpdate("b", 1, 1))
        assert not agg.handle(ProgressUpdate("c", 1, 1))
        assert agg.dropped == 1
        # updating an existing key is always allowed
        assert agg.handle(ProgressUpdate("a", 2, 2))


class TestHeartbeat:
    def test_silent_task_killed_mea_culpa(self):
        clock, store, cluster, scheduler = setup()
        job = make_job(max_retries=2)
        inst = run_job(store, scheduler, job)
        killed_by_backend = []
        hb = HeartbeatMonitor(store, killed_by_backend.append,
                              timeout_ms=60_000)
        hb.track(inst.task_id)
        clock.advance(30_000)
        hb.notify(inst.task_id)
        assert hb.check() == []
        clock.advance(61_000)
        assert hb.check() == [inst.task_id]
        assert killed_by_backend == [inst.task_id]
        # mea-culpa: job went back to waiting without using its retry
        assert store.jobs[job.uuid].state == JobState.WAITING


class TestMonitorGauges:
    def test_starved_user_detection(self):
        clock, store, cluster, scheduler = setup(n_hosts=1, cpus=4.0)
        store.set_share(Share(user=DEFAULT_USER, pool="default",
                              resources=Resources(mem=2000, cpus=4, gpus=1)))
        # hog fills the cluster; starved user waits
        for i in range(2):
            run_job(store, scheduler, make_job(user="hog", cpus=2))
        store.submit_jobs([make_job(user="starved", cpus=2)])
        stats = collect_pool_stats(store, "default")
        assert stats.running_jobs == 2
        assert stats.waiting_jobs == 1
        assert stats.starved_users == 1
        assert stats.used.cpus == 4

    def test_pool_stats_exported_as_gauges(self):
        """collect_pool_stats must publish every PoolStats field it
        computes to the monitor.* gauges, labeled by pool."""
        from cook_tpu.utils.metrics import global_registry

        clock, store, cluster, scheduler = setup(n_hosts=2, cpus=8.0)
        run_job(store, scheduler, make_job(user="u1", mem=500, cpus=2))
        store.submit_jobs([make_job(user="u2", mem=300, cpus=1)])
        stats = collect_pool_stats(store, "default")
        labels = {"pool": "default"}
        g = global_registry.gauge
        assert g("monitor.running_jobs").value(labels) == stats.running_jobs
        assert g("monitor.waiting_jobs").value(labels) == stats.waiting_jobs == 1
        assert g("monitor.running_users").value(labels) == 1
        assert g("monitor.waiting_users").value(labels) == 1
        assert g("monitor.starved_users").value(labels) == stats.starved_users
        assert g("monitor.used_mem").value(labels) == stats.used.mem == 500
        assert g("monitor.used_cpus").value(labels) == 2
        assert g("monitor.waiting_mem").value(labels) == 300
        assert g("monitor.waiting_cpus").value(labels) == 1
        # the gauges render into the exposition with HELP lines
        text = global_registry.render_prometheus()
        assert 'cook_monitor_waiting_mem{pool="default"} 300' in text
        assert "# HELP cook_monitor_starved_users" in text

    def test_collect_all_covers_every_pool(self):
        from cook_tpu.scheduler.monitor import collect_all
        from cook_tpu.utils.metrics import global_registry

        clock, store, cluster, scheduler = setup()
        store.set_pool(Pool(name="batch"))
        store.submit_jobs([make_job(user="u1")])
        store.submit_jobs([make_job(user="u2", pool="batch")])
        stats = collect_all(store)
        assert set(stats) >= {"default", "batch"}
        assert stats["batch"].waiting_jobs == 1
        g = global_registry.gauge("monitor.waiting_jobs")
        assert g.value({"pool": "batch"}) == 1
        assert g.value({"pool": "default"}) == 1


class TestSandboxPublisher:
    def test_batched_publish(self):
        clock, store, cluster, scheduler = setup()
        inst = run_job(store, scheduler, make_job())
        pub = SandboxPublisher(store)
        pub.record_sandbox(inst.task_id, "/sandbox/t1")
        pub.record_exit_code(inst.task_id, 0)
        assert pub.pending_count == 1
        assert pub.publish() == 1
        assert store.instances[inst.task_id].sandbox_directory == "/sandbox/t1"
        assert store.instances[inst.task_id].exit_code == 0


class TestAutoscaleWiring:
    def test_unmatched_demand_reaches_autoscaler(self):
        from cook_tpu.cluster.k8s import FakeKubeApi, KubeCluster, KubeNode

        clock = FakeClock()
        api = FakeKubeApi([KubeNode(name="n0", mem=100, cpus=1)])
        cluster = KubeCluster("k8s", api, clock)
        store = JobStore(clock=clock)
        store.set_pool(Pool(name="default"))
        scheduler = Scheduler(store, [cluster])
        # demand far beyond capacity
        store.submit_jobs([make_job(mem=5000, cpus=4) for _ in range(3)])
        pool = store.pools["default"]
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
        synth = cluster.synthetic_pods()
        assert len(synth) == 3
        assert all(p.mem == 5000 for p in synth)


class TestKillers:
    def test_lingering_task_killed_at_max_runtime(self):
        clock, store, cluster, scheduler = setup()
        job = make_job(max_runtime_ms=50_000, max_retries=5)
        inst = run_job(store, scheduler, job)
        clock.advance(60_000)
        killed = scheduler.kill_lingering_tasks(clock())
        assert killed == [inst.task_id]
        # max-runtime is NOT mea-culpa: consumed the only retry path check
        final = store.instances[inst.task_id]
        assert final.reason_code == 2003

    def test_straggler_killed_by_quantile_rule(self):
        clock, store, cluster, scheduler = setup(n_hosts=4)
        group = Group(
            uuid="g1",
            straggler_handling=StragglerHandling(
                type="quantile-deviation", quantile=0.5, multiplier=2.0),
        )
        jobs = [make_job(group_uuid="g1", max_retries=5) for _ in range(4)]
        store.submit_jobs(jobs, [group])
        pool = store.pools["default"]
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
        insts = [store.job_instances(j.uuid)[0] for j in jobs]
        # three complete quickly, one straggles
        for inst in insts[:3]:
            clock.advance(10_000)
            store.update_instance_state(inst.task_id, InstanceStatus.SUCCESS,
                                        "normal-exit")
        clock.advance(100_000)  # straggler now way past 2x median
        killed = scheduler.kill_stragglers(clock())
        assert killed == [insts[3].task_id]
        assert store.instances[insts[3].task_id].reason_code == 2004


class TestPassport:
    def test_store_events_become_audit_events(self, caplog):
        import logging

        from cook_tpu.utils.logging import attach_passport

        clock, store, cluster, scheduler = setup()
        attach_passport(store)
        with caplog.at_level(logging.INFO, logger="cook_tpu.passport"):
            inst = run_job(store, scheduler, make_job())
            store.update_instance_state(inst.task_id, InstanceStatus.SUCCESS,
                                        1000)
        events = [r.message for r in caplog.records
                  if r.name == "cook_tpu.passport"]
        joined = "\n".join(events)
        assert "job-created" in joined
        assert "job-launched" in joined
        assert "instance-completed" in joined
        assert "job-completed" in joined


class TestHeartbeatEndToEnd:
    def test_rest_heartbeats_feed_the_monitor(self):
        from cook_tpu.rest.api import ApiConfig, CookApi
        from cook_tpu.rest.server import ServerThread
        import requests

        clock, store, cluster, scheduler = setup()
        killed = []
        scheduler.heartbeats = HeartbeatMonitor(store, killed.append,
                                                timeout_ms=60_000)
        srv = ServerThread(CookApi(store, scheduler, ApiConfig())).start()
        try:
            inst = run_job(store, scheduler, make_job(max_retries=3))
            h = {"X-Cook-Requesting-User": "u"}
            r = requests.post(f"{srv.url}/heartbeat/{inst.task_id}", headers=h)
            assert r.status_code == 202
            r = requests.post(f"{srv.url}/heartbeat/nope", headers=h)
            assert r.status_code == 404
            clock.advance(61_000)
            assert scheduler.heartbeats.check() == [inst.task_id]
            assert killed == [inst.task_id]
        finally:
            srv.stop()

    def test_heartbeat_sender_thread(self):
        from cook_tpu.executor.runner import HeartbeatSender

        beats = []

        class FakeSession:
            def post(self, url, timeout=None):
                beats.append(url)

        sender = HeartbeatSender("http://x", "t9", interval_s=0.05,
                                 session=FakeSession()).start()
        import time

        time.sleep(0.3)
        sender.stop()
        assert len(beats) >= 3
        assert beats[0].endswith("/heartbeat/t9")
