"""C++ native solver parity vs the numpy/python references."""
import numpy as np
import pytest

from cook_tpu.ops import cpu_reference as ref
from cook_tpu.ops import native
from tests.test_ops_parity import (
    random_dru_problem,
    random_match_problem,
    random_rebalance_problem,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no toolchain)"
)


@pytest.mark.parametrize("seed", range(3))
def test_native_greedy_match_parity(seed):
    rng = np.random.default_rng(seed)
    demands, avail, totals, feasible = random_match_problem(rng)
    want = ref.ref_greedy_match(demands, avail, totals, feasible)
    got = native.greedy_match(demands, avail, totals, feasible)
    np.testing.assert_array_equal(got, want)
    # and without a mask
    np.testing.assert_array_equal(
        native.greedy_match(demands, avail, totals),
        ref.ref_greedy_match(demands, avail, totals),
    )


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("gpu_mode", [False, True])
def test_native_dru_parity(seed, gpu_mode):
    rng = np.random.default_rng(seed)
    user, mem, cpus, gpus, order_key, md, cd, gd = random_dru_problem(rng)
    want_dru, want_order = ref.ref_dru_order(
        user, mem, cpus, gpus, order_key, md, cd, gd, gpu_mode=gpu_mode
    )
    got_dru, got_order = native.dru_rank(
        user, mem, cpus, gpus, order_key, md, cd, gd, gpu_mode=gpu_mode
    )
    np.testing.assert_allclose(got_dru, want_dru, rtol=1e-12)
    np.testing.assert_array_equal(got_order, want_order)


@pytest.mark.parametrize("seed", range(5))
def test_native_preemption_parity(seed):
    rng = np.random.default_rng(300 + seed)
    task_host, task_dru, task_res, eligible, spare, host_ok = (
        random_rebalance_problem(rng)
    )
    demand = (400.0, 6.0, 0.0)
    want = ref.ref_preemption_decision(
        task_host, task_dru, task_res[:, 0], task_res[:, 1], task_res[:, 2],
        eligible, spare, host_ok, demand, 0.4, 1.0, 0.5,
    )
    got = native.find_preemption(
        task_host, task_dru, task_res, eligible, spare, host_ok,
        np.asarray(demand), 0.4, 1.0, 0.5,
    )
    if want is None:
        assert got is None
        return
    want_host, want_tasks = want
    got_host, got_tasks = got
    if not want_tasks:
        # spare-only: any spare-fitting host acceptable; check it fits
        assert got_tasks == []
        assert np.all(spare[got_host] >= np.asarray(demand))
    else:
        assert got_host == want_host
        assert sorted(got_tasks) == sorted(want_tasks)
