"""fast_cycle rebalancer: ONE device sort per cycle, per-decision masks
in sorted space (ops/rebalance.py sort_rebalance_state +
decide_from_sorted).  Decisions must match the exact per-decision-sort
kernel whenever the intra-cycle approximations (frozen DRU, launches
consume spare only) cannot bite."""
import numpy as np

from cook_tpu.models.entities import (
    DEFAULT_USER,
    Pool,
    Resources,
    Share,
)
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.rebalancer import (
    RebalancerParams,
    rebalance_pool,
)
from tests.conftest import FakeClock, make_job


def _build_store(n_hosts=4, tasks_per_host=2):
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=400, cpus=4, gpus=1)))
    # two hogs holding every host; distinct per-host task sizes so the
    # min-dru ordering is unambiguous
    for h in range(n_hosts):
        for k in range(tasks_per_host):
            user = f"hog{k % 2}"
            job = make_job(user=user, mem=300 + 10 * h, cpus=3)
            store.submit_jobs([job])
            store.create_instance(job.uuid, f"t-{h}-{k}",
                                  hostname=f"h{h}", node_id=f"h{h}",
                                  compute_cluster="m")
    spare = {f"h{h}": Resources(mem=50.0, cpus=1.0) for h in range(n_hosts)}
    return clock, store, spare


def _decision_sig(decisions):
    return [(d.job.uuid, d.hostname, sorted(d.task_ids))
            for d in decisions]


def test_fast_cycle_matches_exact_across_decisions():
    """Pending jobs from users with no running tasks, each decision on a
    different host: the fast path must reproduce the exact kernel's
    decision sequence (host, victims, order)."""
    params_exact = RebalancerParams(safe_dru_threshold=0.0,
                                    min_dru_diff=0.01, max_preemption=10)
    params_fast = RebalancerParams(safe_dru_threshold=0.0,
                                   min_dru_diff=0.01, max_preemption=10,
                                   fast_cycle=True)
    # distinct users -> no frozen-DRU interaction between decisions
    results = []
    for params in (params_exact, params_fast):
        clock, store, spare = _build_store()
        pending = [make_job(user=f"starved{i}", mem=320, cpus=3)
                   for i in range(3)]
        # deterministic uuids so the runs are comparable
        pending = [j.with_(uuid=f"pend-{i}")
                   for i, j in enumerate(pending)]
        store.submit_jobs(pending)
        decisions = rebalance_pool(store, store.pools["default"], pending,
                                   spare, params)
        results.append(_decision_sig(decisions))
    exact_sig, fast_sig = results
    assert exact_sig, "scenario must produce preemptions"
    assert fast_sig == exact_sig


def test_fast_cycle_decisions_internally_consistent():
    """Across many decisions, victims are distinct, above threshold, and
    the freed resources cover each pending demand."""
    params = RebalancerParams(safe_dru_threshold=0.0, min_dru_diff=0.01,
                              max_preemption=20, fast_cycle=True)
    clock, store, spare = _build_store(n_hosts=6, tasks_per_host=3)
    pending = [make_job(user=f"s{i}", mem=300, cpus=3).with_(uuid=f"p{i}")
               for i in range(6)]
    store.submit_jobs(pending)
    decisions = rebalance_pool(store, store.pools["default"], pending,
                               spare, params)
    assert decisions
    seen = set()
    for d in decisions:
        for tid in d.task_ids:
            assert tid not in seen, "victim preempted twice"
            seen.add(tid)
        assert d.min_preempted_dru >= 0.0


def test_fast_cycle_spare_only_host_preempts_nothing():
    """A host whose spare alone covers the demand wins with no victims,
    identically in both modes."""
    for fast in (False, True):
        clock, store, spare = _build_store(n_hosts=2)
        spare["h1"] = Resources(mem=1000.0, cpus=8.0)
        pending = [make_job(user="s", mem=500, cpus=2).with_(uuid="p0")]
        store.submit_jobs(pending)
        params = RebalancerParams(safe_dru_threshold=0.0,
                                  min_dru_diff=0.01, max_preemption=5,
                                  fast_cycle=fast)
        decisions = rebalance_pool(store, store.pools["default"], pending,
                                   spare, params)
        # spare-only decisions carry no task_ids and rebalance_pool drops
        # them from the returned list; no preemption must have happened
        assert all(not d.task_ids for d in decisions)


def test_fast_cycle_threshold_uses_live_dru():
    """A task whose TRUE dru falls below safe_dru_threshold after an
    earlier same-cycle preemption of the same user must be protected in
    fast mode too (live dru values; only the ORDER is frozen)."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=100, cpus=100, gpus=1)))
    # hog's cumulative dru: t0 2.0 (200/100), t1 5.0 (+300), t2 6.0 (+100)
    sizes = [200, 300, 100]
    jobs = []
    for i, mem in enumerate(sizes):
        job = make_job(user="hog", mem=mem, cpus=0.1)
        jobs.append(job)
        store.submit_jobs([job])
        store.create_instance(job.uuid, f"t{i}", hostname=f"h{i}",
                              node_id=f"h{i}", compute_cluster="m")
    spare = {f"h{i}": Resources(mem=10.0, cpus=1.0) for i in range(3)}
    # threshold 3.5: initially t1 (5.0) and t2 (6.0) are preemptable;
    # preempting t1 drops t2's true dru to 3.0 -> protected afterwards
    results = {}
    for fast in (False, True):
        params = RebalancerParams(safe_dru_threshold=3.5,
                                  min_dru_diff=0.01, max_preemption=5,
                                  fast_cycle=fast)
        clock2 = FakeClock()
        store2 = JobStore(clock=clock2)
        store2.set_pool(Pool(name="default"))
        store2.set_share(Share(user=DEFAULT_USER, pool="default",
                               resources=Resources(mem=100, cpus=100,
                                                   gpus=1)))
        for i, mem in enumerate(sizes):
            job = make_job(user="hog", mem=mem, cpus=0.1).with_(
                uuid=f"hog-{i}")
            store2.submit_jobs([job])
            store2.create_instance(job.uuid, f"t{i}", hostname=f"h{i}",
                                   node_id=f"h{i}", compute_cluster="m")
        pending = [
            make_job(user="s1", mem=250, cpus=0.1).with_(uuid="p1"),
            make_job(user="s2", mem=90, cpus=0.1).with_(uuid="p2"),
        ]
        store2.submit_jobs(pending)
        decisions = rebalance_pool(store2, store2.pools["default"],
                                   pending, dict(spare), params)
        results[fast] = _decision_sig(decisions)
    assert results[True] == results[False]
    preempted = {tid for _, _, tids in results[True] for tid in tids}
    assert "t2" not in preempted, "t2's live dru fell below the threshold"


def test_fast_cycle_respects_quota_own_task_restriction():
    """An over-quota user's pending job may only preempt that user's own
    tasks (rebalancer.clj:339-346) — enforced through the sorted-space
    validity mask too."""
    from cook_tpu.models.entities import Quota

    for fast in (False, True):
        clock, store, spare = _build_store(n_hosts=2, tasks_per_host=2)
        store.set_quota(Quota(user="hog0", pool="default",
                              resources=Resources(mem=100, cpus=1),
                              count=1))
        pending = [make_job(user="hog0", mem=320, cpus=3).with_(uuid="p0")]
        store.submit_jobs(pending)
        params = RebalancerParams(safe_dru_threshold=0.0,
                                  min_dru_diff=0.01, max_preemption=5,
                                  fast_cycle=fast)
        decisions = rebalance_pool(store, store.pools["default"], pending,
                                   spare, params)
        for d in decisions:
            for tid in d.task_ids:
                # victims must be hog0's own tasks
                inst_host, inst_k = tid.split("-")[1:]
                assert int(inst_k) % 2 == 0, (fast, tid)
