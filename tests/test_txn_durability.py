"""Durable-on-ack for EVERY mutation type, through the txn pipeline.

Reference bar: every Cook mutation — submit, kill, retry, share/quota,
group ops, pool moves — goes through Datomic's transact-with-retries
(datomic.clj:79) and is durable the moment the REST call returns.  Here
that property comes from `cook_tpu.txn`: one commit pipeline (apply →
journal group-fsync → sync-ack replication) with idempotency keys.
These tests pin:

  * failover durability: each mutation type, acked by the leader in
    sync-ack mode, is present on the standby at ack time and survives
    leader death + standby promotion;
  * idempotent re-apply: a retried commit (same X-Cook-Txn-Id /
    txn_id) on the NEW leader is answered from the replicated
    transaction table, not re-applied;
  * the parked-fetch promotion race: JournalFollower.stop() outlives
    the longest possible in-flight long-poll fetch, so a late response
    from a deposed leader can never clobber a promoted standby;
  * TransactionLog unit semantics: duplicate detection, journal replay
    rebuilding the idempotency table, bounded transient retries.
"""
import threading
import time

import requests

from cook_tpu.components import build_process, shutdown, start_leader_duties
from cook_tpu.control.lease_server import LeaseServer
from cook_tpu.control.replication import JournalFollower
from cook_tpu.models import persistence
from cook_tpu.models.entities import JobState, Pool, Resources, Share
from cook_tpu.models.store import JobStore
from cook_tpu.rest.server import free_port
from cook_tpu.txn import (
    DurabilityPolicy,
    OPS,
    TransactionLog,
    TransientTxnError,
    txn_op,
)
from cook_tpu.utils.config import Settings

H = {"X-Cook-Requesting-User": "u"}
ADMIN = {"X-Cook-Requesting-User": "admin"}


def _settings(port, data_dir, lease_url, **kw):
    return Settings(
        port=port, data_dir=data_dir,
        leader_endpoint=lease_url, leader_ttl_s=3.0,
        clusters=[{
            "kind": "mock", "name": "m1",
            "hosts": [{"node_id": "h0", "mem": 4000, "cpus": 8}],
        }],
        pools=[{"name": "default"}, {"name": "other"}],
        rank_interval_s=3600, match_interval_s=3600,
        **kw,
    )


def _wait(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


# ------------------------------------------------- failover, every mutation


def test_every_mutation_type_survives_promotion_and_dedupes(tmp_path):
    """kill / retry / share / quota / group kill / pool move / config
    update, each acked under sync-ack replication, then the leader dies:
    all of them are present on the promoted standby, and re-committing
    any of them with the same txn id is answered as a duplicate."""
    lease = LeaseServer().start()
    p1 = p2 = None
    try:
        s1 = _settings(free_port(), str(tmp_path / "n1"), lease.url,
                       replication_sync_ack=True,
                       replication_ack_timeout_s=10.0)
        p1 = build_process(s1)
        start_leader_duties(p1, block=False, on_loss=lambda: None)
        assert p1.is_leader()

        s2 = _settings(free_port(), str(tmp_path / "n2"), lease.url)
        p2 = build_process(s2)
        standby = threading.Thread(
            target=start_leader_duties, args=(p2,),
            kwargs={"block": False, "on_loss": lambda: None}, daemon=True)
        standby.start()
        _wait(lambda: p1.api.replication_acks, 15, "standby ack presence")

        base = f"http://127.0.0.1:{s1.port}"
        ja = "e0000000-0000-0000-0000-00000000000a"
        jb = "e0000000-0000-0000-0000-00000000000b"
        jc = "e0000000-0000-0000-0000-00000000000c"
        jd = "e0000000-0000-0000-0000-00000000000d"
        grp = "e0000000-0000-0000-0000-0000000000f0"

        def ok(r, *codes):
            assert r.status_code in codes, (r.status_code, r.text)
            # the durability bound must have been met for every ack
            assert r.headers.get("X-Cook-Replicated") != "false", r.headers
            if r.headers.get("Content-Type", "").startswith(
                    "application/json"):
                assert r.json() is None or not isinstance(r.json(), dict) \
                    or r.json().get("replicated") is not False, r.text
            return r

        # submit A, B, D plus C in group grp
        ok(requests.post(f"{base}/jobs", json={"jobs": [
            {"command": "x", "mem": 100, "cpus": 1, "uuid": u}
            for u in (ja, jb, jd)]},
            headers={**H, "X-Cook-Txn-Id": "t-submit"}, timeout=15), 201)
        ok(requests.post(f"{base}/jobs", json={
            "groups": [{"uuid": grp, "name": "g"}],
            "jobs": [{"command": "x", "mem": 100, "cpus": 1, "uuid": jc,
                      "group": grp}]}, headers=H, timeout=15), 201)
        # kill A
        ok(requests.delete(f"{base}/jobs", params={"job": ja},
                           headers={**H, "X-Cook-Txn-Id": "t-kill"},
                           timeout=15), 204)
        # retry B to 7
        ok(requests.post(f"{base}/retry", json={"job": jb, "retries": 7},
                         headers={**H, "X-Cook-Txn-Id": "t-retry"},
                         timeout=15), 201)
        # share + quota for user u
        ok(requests.post(f"{base}/share", json={
            "user": "u", "share": {"mem": 123.0, "cpus": 4.0}},
            headers={**ADMIN, "X-Cook-Txn-Id": "t-share"}, timeout=15), 201)
        ok(requests.post(f"{base}/quota", json={
            "user": "u", "quota": {"count": 5, "cpus": 9.0}},
            headers={**ADMIN, "X-Cook-Txn-Id": "t-quota"}, timeout=15), 201)
        # group kill (kills C)
        ok(requests.delete(f"{base}/group", params={"uuid": grp},
                           headers={**H, "X-Cook-Txn-Id": "t-group"},
                           timeout=15), 204)
        # pool move D -> other
        r = ok(requests.post(f"{base}/pool-move", json={
            "job": jd, "pool": "other"},
            headers={**ADMIN, "X-Cook-Txn-Id": "t-move"}, timeout=15), 201)
        assert r.json()["moved"] == [jd]
        # dynamic config
        ok(requests.post(f"{base}/incremental-config", json={"flag": "on"},
                         headers={**ADMIN, "X-Cook-Txn-Id": "t-config"},
                         timeout=15), 201)

        # sync-ack means: at ack time the standby already holds ALL of it
        sb = p2.store
        assert sb.jobs[ja].state == JobState.COMPLETED
        assert sb.jobs[jb].max_retries == 7
        assert sb.jobs[jc].state == JobState.COMPLETED
        assert sb.jobs[jd].pool == "other"
        assert sb.shares[("u", "default")].resources.mem == 123.0
        assert sb.quotas[("u", "default")].count == 5
        assert sb.dynamic_config.get("flag") == "on"
        for tid in ("t-submit", "t-kill", "t-retry:" + jb, "t-share",
                    "t-quota", "t-group", "t-move:" + jd, "t-config"):
            assert tid in sb.txn_results, f"txn record {tid} not replicated"

        # leader dies; standby promotes
        shutdown(p1)
        p1 = None
        _wait(lambda: p2.is_leader(), 30, "standby promotion")

        # acked mutations present after failover (and on the standby's
        # own disk: a cold recover of its data dir agrees)
        recovered = persistence.recover(s2.data_dir)
        assert recovered is not None
        assert recovered.jobs[ja].state == JobState.COMPLETED
        assert recovered.jobs[jd].pool == "other"
        assert "t-kill" in recovered.txn_results

        # idempotent re-apply on the NEW leader: same txn ids are
        # answered from the replicated transaction table, not re-applied
        seq_before = p2.store.last_seq()
        dup = p2.api.txn.commit("jobs/kill", {"uuids": [ja]},
                                txn_id="t-kill")
        assert dup.duplicate is True
        dup = p2.api.txn.commit(
            "job/retry", {"uuid": jb, "retries": 7, "increment": False},
            txn_id="t-retry:" + jb)
        assert dup.duplicate is True
        dup = p2.api.txn.commit("job/pool-move",
                                {"uuid": jd, "pool": "other"},
                                txn_id="t-move:" + jd)
        assert dup.duplicate is True
        assert p2.store.last_seq() == seq_before, \
            "duplicate commits must not write new events"

        # and over REST: retried kill with the same X-Cook-Txn-Id on the
        # new leader is a no-op 204
        base2 = f"http://127.0.0.1:{s2.port}"
        r = requests.delete(f"{base2}/jobs", params={"job": ja},
                            headers={**H, "X-Cook-Txn-Id": "t-kill"},
                            timeout=15)
        assert r.status_code == 204
        assert p2.store.last_seq() == seq_before

        # a retried SUBMISSION (same txn id, same explicit uuids) is
        # answered from the transaction table — not "job already exists"
        r = requests.post(f"{base2}/jobs", json={"jobs": [
            {"command": "x", "mem": 100, "cpus": 1, "uuid": u}
            for u in (ja, jb, jd)]},
            headers={**H, "X-Cook-Txn-Id": "t-submit"}, timeout=15)
        assert r.status_code == 201, r.text
        assert sorted(r.json()["jobs"]) == sorted([ja, jb, jd])
        assert p2.store.last_seq() == seq_before
    finally:
        for p in (p1, p2):
            if p is not None:
                shutdown(p)
        lease.stop()


# ------------------------------------------- parked-fetch promotion race


def test_follower_stop_outlives_parked_long_poll(tmp_path):
    """stop() must join the sync thread even when a long-poll fetch is
    parked on the leader: the fetch can be in flight for up to
    timeout_s + long_poll_s, longer than the old timeout_s + 5 join
    bound, and an unjoined thread applying a late response after
    promotion would clobber the new leader's state."""
    s = Settings(
        port=free_port(), data_dir=str(tmp_path / "n1"),
        clusters=[], pools=[{"name": "default"}],
        rank_interval_s=3600, match_interval_s=3600)
    p = build_process(s)
    follower = None
    try:
        url = f"http://127.0.0.1:{s.port}"
        follower = JournalFollower(
            JobStore(), leader_url_fn=lambda: url,
            poll_s=0.05, timeout_s=1.0, long_poll_s=7.0)
        follower.start()
        # catch up, then park the next long-poll (no writes are coming)
        _wait(lambda: follower.synced_events > 0, 10, "follower catch-up")
        time.sleep(0.5)
        t0 = time.monotonic()
        follower.stop()
        elapsed = time.monotonic() - t0
        assert not follower._thread.is_alive(), (
            "stop() returned with the sync thread still running — the "
            "join window does not cover a parked long-poll fetch")
        assert elapsed <= follower.timeout_s + follower.long_poll_s + 5
    finally:
        if follower is not None:
            follower.stop()
        shutdown(p)


# --------------------------------------------------- TransactionLog units


def test_txn_log_duplicate_answered_from_table(tmp_path):
    store = JobStore()
    store.set_pool(Pool(name="default"))
    journal = persistence.attach_journal(store,
                                         str(tmp_path / "journal.jsonl"))
    txn = TransactionLog(store, journal=journal)
    share = Share(user="u", pool="default",
                  resources=Resources(mem=5.0, cpus=1.0))
    out = txn.commit("share/set", {"share": share}, txn_id="t1")
    assert not out.duplicate and out.seq == store.last_seq()
    seq = store.last_seq()
    dup = txn.commit("share/set", {"share": share}, txn_id="t1")
    assert dup.duplicate is True
    assert dup.seq == out.seq and dup.result == out.result
    assert store.last_seq() == seq, "duplicate re-applied"

    # journal replay rebuilds the idempotency table: a recovered store
    # still answers the duplicate without re-applying
    journal.close()
    entries = persistence.read_journal(str(tmp_path / "journal.jsonl"))
    cold = JobStore()
    persistence.apply_journal(cold, entries)
    assert "t1" in cold.txn_results
    dup2 = TransactionLog(cold).commit("share/set", {"share": share},
                                       txn_id="t1")
    assert dup2.duplicate is True
    assert cold.shares[("u", "default")].resources.mem == 5.0


def test_txn_log_snapshot_carries_table():
    src = JobStore()
    src.set_pool(Pool(name="default"))
    TransactionLog(src).commit("config/update", {"updates": {"a": 1}},
                               txn_id="t-cfg")
    state = persistence.snapshot_state(src)
    dst = JobStore()
    persistence.restore_into(dst, state)
    assert "t-cfg" in dst.txn_results
    assert TransactionLog(dst).commit("config/update",
                                      {"updates": {"a": 1}},
                                      txn_id="t-cfg").duplicate is True


def test_txn_log_bounded_transient_retries():
    calls = {"n": 0}

    @txn_op("test/flaky")
    def _flaky(store, payload):
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientTxnError("not yet")
        return {"ok": True}

    try:
        store = JobStore()
        txn = TransactionLog(store, policy=DurabilityPolicy(
            max_attempts=3, retry_backoff_s=0.0))
        out = txn.commit("test/flaky", {})
        assert out.attempts == 3 and out.result == {"ok": True}

        calls["n"] = -100  # always transient within the budget
        try:
            txn.commit("test/flaky", {})
        except TransientTxnError:
            pass
        else:
            raise AssertionError("retry budget not bounded")
    finally:
        del OPS["test/flaky"]
