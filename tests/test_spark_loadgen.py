"""Spark backend integration + deploy-scale load generator, both driven
against a live service process (reference: spark/ patches, simulator/)."""
import time

import pytest

from cook_tpu.client.jobclient import JobClient
from cook_tpu.components import build_process, shutdown, start_leader_duties
from cook_tpu.integrations.spark import (
    SparkCookBackend,
    SparkExecutorSpec,
    parse_master_url,
)
from cook_tpu.rest.server import free_port
from cook_tpu.sim.loadgen import LoadConfig, generate_workload, run_load
from cook_tpu.utils.config import Settings


@pytest.fixture(scope="module")
def service():
    settings = Settings(
        port=free_port(),
        rank_interval_s=0.2, match_interval_s=0.2,
        clusters=[{"kind": "mock", "name": "m", "default_runtime_ms": 600,
                   "hosts": [{"node_id": f"h{i}", "mem": 32000, "cpus": 32}
                             for i in range(4)]}],
    )
    process = build_process(settings)
    start_leader_duties(process, block=False, on_loss=lambda: None)
    yield f"http://127.0.0.1:{settings.port}", process
    shutdown(process)


def test_parse_master_url():
    master = parse_master_url("cook://alice@scheduler:12321")
    assert master.user == "alice"
    assert master.url == "http://scheduler:12321"
    assert parse_master_url("cook://host:1").user == "spark"
    with pytest.raises(ValueError):
        parse_master_url("spark://host:1")
    with pytest.raises(ValueError):
        parse_master_url("cook://nohostport")


def test_spark_backend_fleet_lifecycle(service):
    url, process = service
    host, port = url.rsplit("//", 1)[1].split(":")
    backend = SparkCookBackend(
        f"cook://spark-user@{host}:{port}",
        driver_url="spark://CoarseGrainedScheduler@driver:7077",
        spec=SparkExecutorSpec(executor_cores=2, executor_mem=1024,
                               max_cores=8),
    )
    with backend:
        # spark.cores.max=8 / executor.cores=2 -> 4 executors
        assert len(backend.executors) == 4
        client = JobClient(url, user="spark-user")
        jobs = client.query(list(backend.executors.values()))
        # every executor carries a distinct id + the driver url
        ids = {j["env"]["SPARK_EXECUTOR_ID"] for j in jobs}
        assert len(ids) == 4
        assert all("--driver-url spark://CoarseGrainedScheduler@driver:7077"
                   in j["command"] for j in jobs)
        # executors run on the cluster
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(s == "running"
                   for s in backend.executor_status().values()):
                break
            time.sleep(0.1)
        assert set(backend.executor_status().values()) == {"running"}

        # dynamic allocation: shrink kills the newest executors
        backend.request_total_executors(2)
        assert len(backend.executors) == 2
        assert sorted(backend.executors, key=int) == ["0", "1"]
        # grow again mints fresh ids (Spark never reuses executor ids)
        backend.request_total_executors(3)
        assert "4" in backend.executors
    # context exit killed the fleet
    assert backend.executors == {}
    listed = JobClient(url, user="spark-user").list_jobs(
        "spark-user", states=("running",))
    assert not [j for j in listed if j["name"].startswith("spark-executor")]


def test_workload_generation_deterministic():
    a = generate_workload(LoadConfig(n_jobs=20, seed=5))
    b = generate_workload(LoadConfig(n_jobs=20, seed=5))
    assert [s for _, s in a] == [s for _, s in b]
    offsets = [t for t, _ in a]
    assert offsets == sorted(offsets)


def test_loadgen_against_live_service(service):
    url, process = service
    config = LoadConfig(n_jobs=40, rate_per_minute=6000, n_users=4,
                        seed=3, speedup=10.0)
    report = run_load(url, config, wait_timeout_s=60)
    summary = report.summary()
    assert summary["submitted"] == 40
    assert summary["completed"] == 40
    assert summary["failed"] == 0
    assert summary["submit_ms_p50"] is not None
    assert summary["schedule_ms_p50"] is not None
