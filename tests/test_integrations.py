"""Worker-pool integration adapter over a live server."""
import pytest

from cook_tpu.client.jobclient import JobClient
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.integrations.workerpool import WorkerPool, WorkerSpec
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread
from cook_tpu.scheduler.core import Scheduler
from tests.conftest import FakeClock


@pytest.fixture
def server():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock",
        [MockHost(node_id=f"n{i}", hostname=f"n{i}", mem=32000, cpus=16)
         for i in range(4)],
        clock=clock)
    scheduler = Scheduler(store, [cluster])
    srv = ServerThread(CookApi(store, scheduler, ApiConfig())).start()
    srv.store, srv.scheduler = store, scheduler
    yield srv
    srv.stop()


def test_worker_pool_scale_up_down(server):
    client = JobClient(server.url, user="dask-user")
    pool = WorkerPool(
        client,
        WorkerSpec(command_template="worker --join {address} --cpus {cpus}",
                   mem=1000, cpus=2),
        "tcp://scheduler:8786",
    )
    uuids = pool.scale(6)
    assert len(uuids) == 6
    jobs = client.query(uuids)
    assert all(j["status"] == "waiting" for j in jobs)
    assert all("tcp://scheduler:8786" in j["command"] for j in jobs)
    # all workers share one group
    groups = {g for j in jobs for g in j.get("groups", [])}
    assert len(groups) == 1

    # let the scheduler place them
    p = server.store.pools["default"]
    server.scheduler.rank_cycle(p)
    server.scheduler.match_cycle(p)
    assert pool.status() == {"running": 6}

    # scale down kills the surplus
    pool.scale(2)
    assert len(pool.worker_uuids) == 2
    status = pool.status()
    assert status.get("running") == 2

    pool.close()
    assert pool.worker_uuids == []
