"""Whole-process failover: node A persists, dies; node B recovers the
store from disk and carries on (reference: leader failover replaying from
Datomic)."""
import json

import requests

from cook_tpu.components import build_process, shutdown, start_leader_duties
from cook_tpu.models import persistence
from cook_tpu.models.entities import JobState
from cook_tpu.rest.server import free_port
from cook_tpu.utils.config import Settings


def test_process_failover_via_snapshot(tmp_path):
    data_dir = str(tmp_path / "data")
    lease = str(tmp_path / "lease")
    mock_cluster = [{
        "kind": "mock", "name": "m1",
        "hosts": [{"node_id": "h0", "mem": 4000, "cpus": 8}],
    }]
    s1 = Settings(port=free_port(), data_dir=data_dir,
                  leader_lease_path=lease, clusters=mock_cluster,
                  pools=[{"name": "default"}],
                  rank_interval_s=3600, match_interval_s=3600)
    p1 = build_process(s1)
    url1 = f"http://127.0.0.1:{s1.port}"
    h = {"X-Cook-Requesting-User": "u"}
    r = requests.post(f"{url1}/jobs", json={"jobs": [
        {"command": "x", "mem": 100, "cpus": 1,
         "uuid": "f0000000-0000-0000-0000-000000000001"},
        {"command": "y", "mem": 100, "cpus": 1,
         "uuid": "f0000000-0000-0000-0000-000000000002"},
    ]}, headers=h)
    assert r.status_code == 201
    start_leader_duties(p1, block=False, on_loss=lambda: None)
    loops = {l.name: l for l in p1.loops}
    loops["rank"].fire()
    loops["match"].fire()
    loops["snapshot"].fire()  # persist before "crash"
    shutdown(p1)

    # node B boots from the same data dir and lease
    s2 = Settings(port=free_port(), data_dir=data_dir,
                  leader_lease_path=lease, clusters=mock_cluster,
                  pools=[{"name": "default"}],
                  rank_interval_s=3600, match_interval_s=3600)
    p2 = build_process(s2)
    try:
        url2 = f"http://127.0.0.1:{s2.port}"
        r = requests.get(
            f"{url2}/jobs/f0000000-0000-0000-0000-000000000001", headers=h)
        assert r.status_code == 200
        job = r.json()
        assert job["status"] == "running"  # state survived the failover
        assert len(job["instances"]) == 1
        # the new leader keeps scheduling
        start_leader_duties(p2, block=False, on_loss=lambda: None)
        assert p2.is_leader()
        # journal (incl. the segment rotated aside at snapshot time) has
        # the submission events
        events = (persistence.read_journal(f"{data_dir}/journal.jsonl")
                  + persistence.read_journal(f"{data_dir}/journal.jsonl.1"))
        assert any(e["kind"] == "job/created" for e in events)
    finally:
        shutdown(p2)


def test_post_snapshot_writes_survive_crash(tmp_path):
    """Writes acknowledged AFTER the last snapshot must survive a hard crash
    via journal replay (the advisor's round-1 finding: the old recovery
    loaded only the snapshot, silently losing up to snapshot_interval_s of
    acknowledged jobs)."""
    data_dir = str(tmp_path / "data")
    mock_cluster = [{
        "kind": "mock", "name": "m1",
        "hosts": [{"node_id": "h0", "mem": 4000, "cpus": 8}],
    }]

    def settings():
        return Settings(port=free_port(), data_dir=data_dir,
                        leader_lease_path=str(tmp_path / "lease"),
                        clusters=mock_cluster, pools=[{"name": "default"}],
                        rank_interval_s=3600, match_interval_s=3600)

    s1 = settings()
    p1 = build_process(s1)
    h = {"X-Cook-Requesting-User": "u"}
    url1 = f"http://127.0.0.1:{s1.port}"
    pre = "f0000000-0000-0000-0000-00000000000a"
    post = "f0000000-0000-0000-0000-00000000000b"
    assert requests.post(f"{url1}/jobs", json={"jobs": [
        {"command": "x", "mem": 100, "cpus": 1, "uuid": pre},
    ]}, headers=h).status_code == 201
    start_leader_duties(p1, block=False, on_loss=lambda: None)
    loops = {l.name: l for l in p1.loops}
    loops["snapshot"].fire()
    # acknowledged after the snapshot: only the journal has it
    assert requests.post(f"{url1}/jobs", json={"jobs": [
        {"command": "y", "mem": 100, "cpus": 1, "uuid": post,
         "application": {"name": "app", "version": "7"}},
    ]}, headers=h).status_code == 201
    # hard crash: no further snapshot, no graceful close
    shutdown(p1)

    s2 = settings()
    p2 = build_process(s2)
    try:
        assert pre in p2.store.jobs
        assert post in p2.store.jobs, "post-snapshot write lost on failover"
        job = p2.store.jobs[post]
        assert job.state == JobState.WAITING
        assert job.application is not None and job.application.name == "app"
        assert p2.store.recovered_stats["journal_replayed"] >= 1
        url2 = f"http://127.0.0.1:{s2.port}"
        r = requests.get(f"{url2}/jobs/{post}", headers=h)
        assert r.status_code == 200
    finally:
        shutdown(p2)
