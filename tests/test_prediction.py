"""Prediction-assisted speculative cycles (scheduler/prediction.py):
predictor edge cases (cold start, single sample, outliers, quantile
monotonicity), the speculation commit rule — an epoch-stale speculation
is DROPPED, never repaired (the inducing race: a store mutation landing
between dispatch and commit vetoes the commit) — the pipelined path, the
predicted-duration backfill term, and the completion-heavy A/B
(>= 20% of cycles served from speculation, lower cycle-start-to-first-
launch p50, identical placements on the standard trace)."""
import numpy as np
import pytest

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.matcher import MatchConfig
from cook_tpu.scheduler.prediction import (
    DROP_EPOCH_STALE,
    DROP_PREDICTION_MISS,
    DROP_PREDICTOR_COLD,
    QuantileRuntimePredictor,
    SpeculationGuard,
    command_fingerprint,
    pre_launch_ms,
)
from tests.conftest import FakeClock, make_job


# ----------------------------------------------------------- the predictor


def test_predictor_cold_start_returns_none():
    p = QuantileRuntimePredictor(min_samples=3)
    assert p.predict_runtime_ms("u", "train.py") is None
    p.observe("u", "train.py", 1000)
    p.observe("u", "train.py", 1000)
    assert p.predict_runtime_ms("u", "train.py") is None  # 2 < min_samples
    p.observe("u", "train.py", 1000)
    assert p.predict_runtime_ms("u", "train.py") == pytest.approx(1000)


def test_predictor_single_sample_when_allowed():
    p = QuantileRuntimePredictor(min_samples=1)
    p.observe("u", "cmd", 4200)
    assert p.predict_runtime_ms("u", "cmd") == pytest.approx(4200)


def test_predictor_outlier_robustness():
    """One wild outlier must not drag the rolling-quantile estimate far
    from the workload's typical runtime (the median stays put)."""
    p = QuantileRuntimePredictor(min_samples=3)
    for _ in range(9):
        p.observe("u", "cmd", 100)
    p.observe("u", "cmd", 1_000_000)
    assert p.predict_runtime_ms("u", "cmd", quantile=0.5) \
        == pytest.approx(100)
    # even the default p75 stays inside the bulk
    assert p.predict_runtime_ms("u", "cmd") <= 200


def test_predictor_quantile_monotonicity():
    p = QuantileRuntimePredictor(min_samples=3)
    for v in (100, 200, 300, 400, 500, 600, 700, 800):
        p.observe("u", "cmd", v)
    estimates = [p.predict_runtime_ms("u", "cmd", quantile=q)
                 for q in (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)]
    assert estimates == sorted(estimates)
    assert estimates[-1] == pytest.approx(800)


def test_predictor_window_evicts_old_samples():
    p = QuantileRuntimePredictor(min_samples=1, window=4)
    for _ in range(10):
        p.observe("u", "cmd", 10_000)
    for _ in range(4):  # the window is now entirely the new regime
        p.observe("u", "cmd", 100)
    assert p.predict_runtime_ms("u", "cmd") == pytest.approx(100)


def test_predictor_key_lru_bound():
    p = QuantileRuntimePredictor(min_samples=1, max_keys=3)
    for i in range(6):
        p.observe(f"u{i}", "cmd", 100)
    assert len(p._samples) == 3
    assert p.predict_runtime_ms("u0", "cmd") is None  # evicted
    assert p.predict_runtime_ms("u5", "cmd") is not None


def test_command_fingerprint_distinguishes_commands():
    a = command_fingerprint("train.py --lr 1e-3")
    assert a == command_fingerprint("train.py --lr 1e-3")
    assert a != command_fingerprint("train.py --lr 3e-4")
    assert command_fingerprint("").startswith("#")
    # REST admits whitespace-only commands (`if not command` passes " ");
    # the fingerprint must not crash the completion watcher on them
    assert command_fingerprint(" ").startswith("#")
    assert command_fingerprint("\t\n").startswith("#")
    p = QuantileRuntimePredictor(min_samples=1)
    p.observe("u", " ", 500)
    assert p.predict_runtime_ms("u", " ") == pytest.approx(500)


def test_predictor_feeds_from_store_completions():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    p = QuantileRuntimePredictor(min_samples=1).attach(store)
    job = make_job(user="alice").with_(command="run.sh")
    store.submit_jobs([job])
    store.create_instance(job.uuid, "t1", hostname="h0")
    clock.advance(7000)
    from cook_tpu.models.entities import InstanceStatus

    store.update_instance_state("t1", InstanceStatus.SUCCESS, "normal-exit")
    assert p.predict_runtime_ms("alice", "run.sh") == pytest.approx(7000)


# --------------------------------------------------------------- the guard


def _fake_event(kind, data):
    from cook_tpu.models.store import Event

    return Event(seq=0, kind=kind, data=data)


def test_guard_unexpected_event_marks_stale():
    g = SpeculationGuard()
    token = g.begin("default")
    g.expect(token, [("instance/status", "t1", "success")])
    g._on_event(_fake_event("quota/set", {"user": "u"}))
    ok, reason = g.finish(token)
    assert not ok and reason == DROP_EPOCH_STALE


def test_guard_expected_completion_confirms():
    g = SpeculationGuard()
    token = g.begin("default")
    g.expect(token, [("instance/status", "t1", "success"),
                     ("job/state", "j1", "completed")])
    g._on_event(_fake_event("instance/status",
                            {"task_id": "t1", "status": "success"}))
    g._on_event(_fake_event("job/state",
                            {"uuid": "j1", "state": "completed"}))
    ok, reason = g.finish(token)
    assert ok and reason == ""


def test_guard_missing_confirmation_is_prediction_miss():
    g = SpeculationGuard()
    token = g.begin("default")
    g.expect(token, [("instance/status", "t1", "success")])
    ok, reason = g.finish(token)
    assert not ok and reason == DROP_PREDICTION_MISS


def test_guard_assumed_task_failing_is_stale():
    """The predicted task finishing with the WRONG terminal status is an
    unexpected event (a failure re-queues the job), not a confirmation."""
    g = SpeculationGuard()
    token = g.begin("default")
    g.expect(token, [("instance/status", "t1", "success")])
    g._on_event(_fake_event("instance/status",
                            {"task_id": "t1", "status": "failed"}))
    ok, reason = g.finish(token)
    assert not ok and reason == DROP_EPOCH_STALE


def test_guard_pool_scoping():
    """Job-lifecycle events attributable to ANOTHER pool leave the token
    committable (pool-local match inputs are untouched); unattributable
    kinds stay global and veto every token."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="a"))
    store.set_pool(Pool(name="b"))
    other = make_job(user="u", pool="b")
    store.submit_jobs([other])
    g = SpeculationGuard(store)
    token = g.begin("a")
    g._on_event(_fake_event("job/state",
                            {"uuid": other.uuid, "state": "completed"}))
    ok, _ = g.finish(token)
    assert ok, "pool-b lifecycle event must not veto pool-a's token"
    token = g.begin("a")
    g._on_event(_fake_event("pool/capacity", {"uuid": "x"}))
    ok, reason = g.finish(token)
    assert not ok and reason == DROP_EPOCH_STALE


# ------------------------------------------------- speculative cycles (e2e)


def one_host_scenario(n_jobs=3, runtime_ms=10_000, **config_kw):
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock",
        [MockHost(node_id="h0", hostname="h0", mem=1000, cpus=4,
                  pool="default")],
        clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=0),
        speculation=True,
        speculation_horizon_ms=runtime_ms,
        predictor_min_samples=1,
        **config_kw))
    jobs = [make_job(user="u0", mem=1000, cpus=4).with_(
        uuid=f"j{i}", expected_runtime_ms=runtime_ms)
        for i in range(n_jobs)]
    store.submit_jobs(jobs)
    return clock, store, cluster, scheduler, jobs


def run_cycle(scheduler, store):
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    return outcome, scheduler.recorder.records(limit=1)[0]


def advance_wave(clock, cluster, ms=10_000):
    clock.advance(ms)
    cluster.advance_to(clock())


def test_speculative_cycle_hit_end_to_end():
    clock, store, cluster, scheduler, jobs = one_host_scenario()
    _, r1 = run_cycle(scheduler, store)           # j0 fresh; predictor cold
    assert r1.speculation == "none"
    advance_wave(clock, cluster)                  # j0 completes (observed)
    _, r2 = run_cycle(scheduler, store)           # j1 fresh; speculates j2
    assert r2.speculation == "none"
    assert r2.speculation_drop == DROP_PREDICTOR_COLD
    advance_wave(clock, cluster)                  # j1 completes as predicted
    out3, r3 = run_cycle(scheduler, store)        # served from speculation
    assert r3.speculation == "hit" and r3.speculative
    assert [j.uuid for j, _ in out3.matched] == ["j2"]
    # the hit cycle never paid tensor_build or a solve
    assert "tensor_build" not in r3.phases and "solve" not in r3.phases
    assert "speculation_commit" in r3.phases
    assert r3.backend.startswith("spec-")
    stats = scheduler.speculator.stats_json()
    assert stats["hits"] == 1 and stats["dropped"] == 0


def test_epoch_stale_speculation_never_commits():
    """THE inducing race: a store mutation landing between speculative
    dispatch and commit must veto the commit — the speculation is
    dropped (reason epoch-stale), never repaired, and the cycle solves
    fresh against the mutated state."""
    clock, store, cluster, scheduler, jobs = one_host_scenario()
    run_cycle(scheduler, store)
    advance_wave(clock, cluster)
    run_cycle(scheduler, store)                   # speculation in flight
    assert scheduler.speculator.stats_json()["inflight"] == ["default"]
    # the race: a new submission lands before the next cycle
    late = make_job(user="u9", mem=100, cpus=1).with_(uuid="late")
    store.submit_jobs([late])
    advance_wave(clock, cluster)
    out3, r3 = run_cycle(scheduler, store)
    assert r3.speculation == "dropped"
    assert r3.speculation_drop == DROP_EPOCH_STALE
    assert not r3.speculative
    # the fresh solve saw the mutated state: the late job was considered
    matched = {j.uuid for j, _ in out3.matched}
    assert "late" in matched
    assert scheduler.speculator.stats_json()["drop_reasons"] \
        == {DROP_EPOCH_STALE: 1}


def test_prediction_miss_drops_instead_of_committing():
    """An assumed completion that does NOT land by the next cycle vetoes
    the commit: the speculative offers counted capacity that is still
    occupied."""
    clock, store, cluster, scheduler, jobs = one_host_scenario()
    run_cycle(scheduler, store)
    advance_wave(clock, cluster)
    run_cycle(scheduler, store)
    assert scheduler.speculator.stats_json()["inflight"] == ["default"]
    # advance less than the real runtime: the predicted completion
    # (eta = exactly one horizon out) has NOT landed at the next cycle
    clock.advance(2000)
    cluster.advance_to(clock())
    out3, r3 = run_cycle(scheduler, store)
    assert r3.speculation == "dropped"
    assert r3.speculation_drop == DROP_PREDICTION_MISS
    assert not out3.matched  # host genuinely still busy


def test_no_speculation_while_completion_constraint_active():
    """Under the estimated-completion constraint feasibility rows are
    clock/predictor-state-dependent, so a speculative solve can never be
    provably identical to a fresh one — dispatch must refuse outright
    (the encode cache bypasses itself in this mode for the same
    reason)."""
    clock, store, cluster, scheduler, jobs = one_host_scenario()
    scheduler.config.match.completion_multiplier = 1.5
    scheduler.config.match.host_lifetime_mins = 100.0
    run_cycle(scheduler, store)
    advance_wave(clock, cluster)
    run_cycle(scheduler, store)
    assert scheduler.speculator.stats_json()["inflight"] == []
    assert scheduler.speculator.stats_json()["dispatched"] == 0


def test_disabled_kill_switch_drops_inflight():
    clock, store, cluster, scheduler, jobs = one_host_scenario()
    run_cycle(scheduler, store)
    advance_wave(clock, cluster)
    run_cycle(scheduler, store)
    scheduler.speculator.enabled = False
    advance_wave(clock, cluster)
    _, r3 = run_cycle(scheduler, store)
    assert r3.speculation == "dropped"
    assert r3.speculation_drop == "disabled"


def test_offers_changed_drops():
    """A host appearing between dispatch and commit changes the offer
    STRUCTURE without any store event — the fingerprint check drops the
    speculation."""
    clock, store, cluster, scheduler, jobs = one_host_scenario(n_jobs=4)
    run_cycle(scheduler, store)
    advance_wave(clock, cluster)
    run_cycle(scheduler, store)
    assert scheduler.speculator.stats_json()["inflight"] == ["default"]
    new_host = MockHost(node_id="h1", hostname="h1", mem=1000, cpus=4,
                        pool="default")
    cluster.hosts[new_host.node_id] = new_host  # no store event fires
    advance_wave(clock, cluster)
    _, r3 = run_cycle(scheduler, store)
    assert r3.speculation == "dropped"
    assert r3.speculation_drop == "offers-changed"


def test_pipelined_speculation_hit():
    clock = FakeClock()
    store = JobStore(clock=clock)
    hosts = []
    for p in range(2):
        store.set_pool(Pool(name=f"pool{p}"))
        hosts.append(MockHost(node_id=f"p{p}h0", hostname=f"p{p}h0",
                              mem=1000, cpus=4, pool=f"pool{p}"))
    cluster = MockCluster("mock", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=0), speculation=True,
        speculation_horizon_ms=10_000, predictor_min_samples=1))
    jobs = []
    for p in range(2):
        for i in range(3):
            jobs.append(make_job(user="u0", pool=f"pool{p}", mem=1000,
                                 cpus=4).with_(uuid=f"p{p}j{i}",
                                               expected_runtime_ms=10_000))
    store.submit_jobs(jobs)
    pools = list(store.pools.values())

    def pcycle():
        for pool in pools:
            scheduler.rank_cycle(pool)
        scheduler.match_cycle_pipelined()
        return scheduler.recorder.records(limit=2)

    pcycle()
    advance_wave(clock, cluster)
    pcycle()
    advance_wave(clock, cluster)
    records = pcycle()
    for r in records:
        # one pool's predicted completions must not veto the other's
        # speculation (pool-scoped guard)
        assert r.speculation == "hit" and r.pipelined
    for p in range(2):
        assert store.jobs[f"p{p}j2"].state.value == "running"


def test_speculative_hit_placements_equal_fresh_solve():
    """A committed speculation's placements must equal what a fresh
    solve at cycle N+1 would have produced (the commit rule's whole
    claim) — run the identical scenario with speculation on and off and
    compare every placement."""
    def run(speculation):
        clock = FakeClock()
        store = JobStore(clock=clock)
        store.set_pool(Pool(name="default"))
        cluster = MockCluster(
            "mock",
            [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=1000, cpus=4,
                      pool="default") for i in range(2)],
            clock=clock)
        scheduler = Scheduler(store, [cluster], SchedulerConfig(
            match=MatchConfig(chunk=0), speculation=speculation,
            speculation_horizon_ms=10_000, predictor_min_samples=1))
        jobs = [make_job(user=f"u{i % 2}", mem=1000, cpus=4).with_(
            uuid=f"j{i}", expected_runtime_ms=10_000) for i in range(8)]
        store.submit_jobs(jobs)
        placements = []
        for _ in range(6):
            out, _ = run_cycle(scheduler, store)
            placements.extend((j.uuid, o.hostname) for j, o in out.matched)
            advance_wave(clock, cluster)
        return placements

    assert run(True) == run(False)


# ------------------------------------------------------------- A/B (sim)


def completion_heavy_results(speculate):
    from cook_tpu.scheduler.core import SchedulerConfig as SC
    from cook_tpu.sim.loadgen import completion_heavy_trace
    from cook_tpu.sim.simulator import SimConfig, Simulator

    jobs, hosts = completion_heavy_trace(jobs=24, hosts=4)
    config = SimConfig(cycle_ms=30_000, max_cycles=40, speculate=speculate,
                       scheduler=SC(device_telemetry=False))
    return Simulator(jobs, hosts, config).run()


def test_ab_completion_heavy_speculation():
    """ISSUE-10 acceptance: >= 20% of cycles served from speculation and
    a lower cycle-start-to-first-launch p50, with identical placements."""
    base = completion_heavy_results(False)
    spec = completion_heavy_results(True)
    b, s = base.speculation_stats(), spec.speculation_stats()
    assert b["hits"] == 0
    assert s["hit_fraction"] >= 0.2, s
    assert s["pre_launch_p50_ms"] < b["pre_launch_p50_ms"], (s, b)

    def placements(result):
        return sorted((r["job_uuid"], r["start_ms"], r["host"])
                      for r in result.rows if r["start_ms"] is not None)

    assert placements(base) == placements(spec)


def test_ab_standard_trace_identical_placements():
    """On the standard synthetic trace (varied runtimes — predictions
    routinely miss), speculation must change NO placement: every commit
    is provably identical to the fresh solve, every miss drops."""
    from cook_tpu.scheduler.core import SchedulerConfig as SC
    from cook_tpu.sim.simulator import SimConfig, Simulator, synth_trace

    def run(speculate):
        jobs, hosts = synth_trace(40, 6, n_users=4, seed=3,
                                  mean_runtime_ms=45_000)
        config = SimConfig(cycle_ms=30_000, max_cycles=60,
                           speculate=speculate,
                           scheduler=SC(device_telemetry=False))
        result = Simulator(jobs, hosts, config).run()
        return sorted((r["job_uuid"], r["start_ms"], r["host"])
                      for r in result.rows if r["start_ms"] is not None)

    assert run(True) == run(False)


def test_pre_launch_ms_helper():
    record = {"phases": {"rank": 1.0, "tensor_build": 0.002,
                         "solve": 0.003, "launch": 0.5}}
    assert pre_launch_ms(record) == pytest.approx(5.0)


# -------------------------------------------------- backfill scoring term


def test_dru_backfill_reorders_within_bound():
    import jax.numpy as jnp

    from cook_tpu.ops.dru import DruTasks, dru_rank

    # two users, equal shares, one pending task each with identical
    # demand -> equal DRU; the backfill term must put the predicted-short
    # task first, and weight 0 must reproduce the unadjusted order
    tasks = DruTasks(
        user=jnp.asarray([0, 1], dtype=jnp.int32),
        mem=jnp.asarray([100.0, 100.0]),
        cpus=jnp.asarray([1.0, 1.0]),
        gpus=jnp.zeros(2),
        order_key=jnp.asarray([0.0, 1.0]),
        valid=jnp.asarray([True, True]),
    )
    div = jnp.asarray([1000.0, 1000.0])
    plain = dru_rank(tasks, div, div, div)
    assert list(np.asarray(plain.order)) == [0, 1]
    # task 1 predicted short (frac 0.1), task 0 long (frac 1.0)
    adjusted = dru_rank(tasks, div, div, div,
                        backfill=jnp.asarray([1.0, 0.1]),
                        backfill_weight=jnp.float32(0.05))
    assert list(np.asarray(adjusted.order)) == [1, 0]
    # raw dru column is NOT rewritten by the term
    np.testing.assert_allclose(np.asarray(adjusted.dru),
                               np.asarray(plain.dru))
    # bounded: a materially lower-DRU task cannot be jumped
    tasks2 = tasks._replace(mem=jnp.asarray([100.0, 900.0]))
    adjusted2 = dru_rank(tasks2, div, div, div,
                         backfill=jnp.asarray([1.0, 0.0]),
                         backfill_weight=jnp.float32(0.05))
    assert list(np.asarray(adjusted2.order)) == [0, 1]


def test_rank_pool_backfill_prefers_predicted_short_jobs():
    from cook_tpu.scheduler.ranking import rank_pool

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    long_job = make_job(user="a", mem=100, cpus=1).with_(
        uuid="long", command="long.sh")
    short_job = make_job(user="b", mem=100, cpus=1).with_(
        uuid="short", command="short.sh")
    store.submit_jobs([long_job, short_job])
    predictor = QuantileRuntimePredictor(min_samples=1)
    predictor.observe("a", "long.sh", 600_000)
    predictor.observe("b", "short.sh", 10_000)
    pool = store.pools["default"]
    plain = rank_pool(store, pool)
    assert [j.uuid for j in plain.jobs] == ["long", "short"]  # submit order
    boosted = rank_pool(store, pool, predictor=predictor,
                        backfill_weight=0.05, backfill_norm_ms=600_000)
    assert [j.uuid for j in boosted.jobs] == ["short", "long"]
    # weight 0 keeps the exact unadjusted order
    zero = rank_pool(store, pool, predictor=predictor, backfill_weight=0.0)
    assert [j.uuid for j in zero.jobs] == [j.uuid for j in plain.jobs]


def test_estimated_end_times_uses_predictor():
    from cook_tpu.scheduler.matcher import estimated_end_times

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    job = make_job(user="a", mem=100, cpus=1).with_(
        uuid="noest", command="run.sh", expected_runtime_ms=0)
    store.submit_jobs([job])
    config = MatchConfig(completion_multiplier=1.5,
                         host_lifetime_mins=100.0)
    # no declared expected runtime and no predictor -> no estimate
    assert estimated_end_times(store, [job], config)[0] == -1.0
    predictor = QuantileRuntimePredictor(min_samples=1)
    predictor.observe("a", "run.sh", 60_000)
    est = estimated_end_times(store, [job], config, predictor=predictor)
    assert est[0] == pytest.approx(clock() + 90_000)


# ----------------------------------------------------------- REST surface


def test_debug_predictions_endpoint():
    import requests

    from cook_tpu.rest.api import ApiConfig, CookApi
    from cook_tpu.rest.server import ServerThread

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock", [MockHost(node_id="h0", hostname="h0", mem=1000, cpus=4,
                          pool="default")], clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=0), speculation=True,
        predictor_min_samples=1))
    scheduler.predictor.observe("alice", "run.sh", 5000)
    api = CookApi(store, scheduler, ApiConfig())
    server = ServerThread(api).start()
    try:
        r = requests.get(
            f"{server.url}/debug/predictions",
            headers={"X-Cook-Requesting-User": "alice"})
        assert r.status_code == 200
        body = r.json()
        assert body["enabled"] is True
        assert body["predictor"]["observations"] == 1
        assert body["speculation"]["hits"] == 0
        assert "drop_reasons" in body["speculation"]
    finally:
        server.stop()
