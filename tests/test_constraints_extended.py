"""Disk-type, estimated-completion, and port-resource constraints
(reference: constraints.clj:164 disk, :385 estimated completion;
mesos/task.clj + mesos_mock.clj:162 port resources)."""
import numpy as np

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import JobState, Pool, Resources
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.matcher import MatchConfig
from tests.conftest import FakeClock, make_job


def setup(hosts, match=None):
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster("m", hosts, clock=clock)
    config = SchedulerConfig(match=match or MatchConfig())
    return clock, store, cluster, Scheduler(store, [cluster], config)


def cycle(scheduler, store):
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    return scheduler.match_cycle(pool)


# ------------------------------------------------------------------ disk


def test_typed_disk_request_only_matches_advertising_hosts():
    clock, store, cluster, sched = setup([
        MockHost(node_id="std", hostname="std", mem=8000, cpus=32,
                 disk=10_000, attributes=(("disk-type", "standard"),)),
        MockHost(node_id="ssd", hostname="ssd", mem=8000, cpus=32,
                 disk=10_000, attributes=(("disk-type", "pd-ssd"),)),
    ])
    job = make_job(mem=100, cpus=1,
                   resources=Resources(mem=100, cpus=1, disk=500,
                                       disk_type="pd-ssd"))
    store.submit_jobs([job])
    outcome = cycle(sched, store)
    [(j, offer)] = outcome.matched
    assert offer.hostname == "ssd"


def test_disk_space_binpacked_as_fourth_resource():
    clock, store, cluster, sched = setup([
        MockHost(node_id="small", hostname="small", mem=8000, cpus=32,
                 disk=100),
        MockHost(node_id="big", hostname="big", mem=8000, cpus=32,
                 disk=5000),
    ])
    job = make_job(mem=100, cpus=1,
                   resources=Resources(mem=100, cpus=1, disk=800))
    store.submit_jobs([job])
    outcome = cycle(sched, store)
    [(j, offer)] = outcome.matched
    assert offer.hostname == "big"


def test_typed_disk_unsatisfiable_stays_pending():
    clock, store, cluster, sched = setup([
        MockHost(node_id="std", hostname="std", mem=8000, cpus=32,
                 disk=10_000, attributes=(("disk-type", "standard"),)),
    ])
    job = make_job(mem=100, cpus=1,
                   resources=Resources(mem=100, cpus=1, disk=500,
                                       disk_type="pd-ssd"))
    store.submit_jobs([job])
    outcome = cycle(sched, store)
    assert not outcome.matched and outcome.unmatched


# ------------------------------------------- estimated completion


def est_config():
    return MatchConfig(completion_multiplier=1.5, host_lifetime_mins=60,
                       agent_start_grace_mins=10)


def test_estimated_completion_avoids_dying_hosts():
    """A job expected to run 30 min (x1.5 = 45 min) must skip a host that
    dies in 20 min but may take one that dies in 50."""
    clock, store, cluster, sched = setup([
        # started 40 min ago -> dies in 20 min
        MockHost(node_id="old", hostname="old", mem=8000, cpus=32,
                 attributes=(("host-start-time", str(10_000_000 - 40 * 60)),)),
        # started 10 min ago -> dies in 50 min
        MockHost(node_id="fresh", hostname="fresh", mem=8000, cpus=32,
                 attributes=(("host-start-time", str(10_000_000 - 10 * 60)),)),
    ], match=est_config())
    clock.now_ms = 10_000_000_000  # epoch 1e7 s
    job = make_job(mem=100, cpus=1, expected_runtime_ms=30 * 60_000)
    store.submit_jobs([job])
    outcome = cycle(sched, store)
    [(j, offer)] = outcome.matched
    assert offer.hostname == "fresh"


def test_estimated_completion_ignores_hosts_without_start_time():
    clock, store, cluster, sched = setup([
        MockHost(node_id="h", hostname="h", mem=8000, cpus=32),
    ], match=est_config())
    job = make_job(mem=100, cpus=1, expected_runtime_ms=10**9)
    store.submit_jobs([job])
    assert cycle(sched, store).matched


def test_estimated_completion_counts_agent_removed_runtimes():
    """A job with no expected runtime whose previous instance died with
    the host after 45 min inherits that runtime as its estimate."""
    clock, store, cluster, sched = setup([
        MockHost(node_id="old", hostname="old", mem=8000, cpus=32,
                 attributes=(("host-start-time", str(10_000_000 - 40 * 60)),)),
        MockHost(node_id="fresh", hostname="fresh", mem=8000, cpus=32,
                 attributes=(("host-start-time", str(10_000_000 - 10 * 60)),)),
    ], match=est_config())
    from cook_tpu.models.entities import InstanceStatus

    job = make_job(mem=100, cpus=1, max_retries=3)
    store.submit_jobs([job])
    clock.now_ms = 0
    store.create_instance(job.uuid, "t-prev", hostname="gone",
                          node_id="gone", compute_cluster="m")
    clock.now_ms = 45 * 60_000
    store.update_instance_state("t-prev", InstanceStatus.FAILED,
                                "node-removed")
    clock.now_ms = 10_000_000_000
    outcome = cycle(sched, store)
    [(j, offer)] = outcome.matched
    assert offer.hostname == "fresh"


# ----------------------------------------------------- checkpoint overhead


def test_checkpoint_overhead_applied_at_match_time():
    """A checkpointing job's memory demand carries the tooling overhead
    from MATCH time onward (calculate-effective-resources,
    api.clj:1152): placement, the TaskSpec, and the checkpoint env all
    agree, so a backend can never direct-bind a pod the kubelet must
    reject."""
    from cook_tpu.models.entities import Checkpoint

    clock, store, cluster, sched = setup(
        [
            # only big fits 400 + 200 overhead
            MockHost(node_id="small", hostname="small", mem=500, cpus=32),
            MockHost(node_id="big", hostname="big", mem=1000, cpus=32),
        ],
        match=MatchConfig(checkpoint_memory_overhead_mb=200),
    )
    job = make_job(mem=400, cpus=1,
                   checkpoint=Checkpoint(mode="auto", periodic_sec=120,
                                         preserve_paths=("/data", "/ckpt")))
    store.submit_jobs([job])
    outcome = cycle(sched, store)
    [(j, offer)] = outcome.matched
    assert offer.hostname == "big"
    [rt] = cluster.running.values()
    assert rt.spec.mem == 600  # 400 + 200, visible to the backend
    env = dict(rt.spec.env)
    assert env["COOK_CHECKPOINT_MODE"] == "auto"
    assert env["COOK_CHECKPOINT_PERIOD_SEC"] == "120"
    assert env["COOK_CHECKPOINT_PRESERVE_PATHS"] == "/data:/ckpt"


# ------------------------------------------------------------------ ports


def test_port_assignment_and_release():
    clock, store, cluster, sched = setup([
        MockHost(node_id="h", hostname="h", mem=8000, cpus=32,
                 ports=((31000, 31002),)),
    ])
    jobs = [make_job(mem=100, cpus=1,
                     resources=Resources(mem=100, cpus=1, ports=2),
                     expected_runtime_ms=60_000)
            for _ in range(2)]
    store.submit_jobs(jobs)
    outcome = cycle(sched, store)
    # 3 free ports: the first 2-port job fits, the second must wait
    assert len(outcome.matched) == 1
    assert len(outcome.unmatched) == 1
    [rt] = cluster.running.values()
    assert len(rt.spec.ports) == 2
    assert set(rt.spec.ports) <= {31000, 31001, 31002}
    env = dict(rt.spec.env)
    assert env["PORT0"] == str(rt.spec.ports[0])
    assert env["PORT1"] == str(rt.spec.ports[1])
    # offer shrank to the single leftover port
    [offer] = cluster.pending_offers("default")
    assert offer.port_count() == 1
    # completion releases the ports; the waiting job then fits
    clock.now_ms += 120_000
    cluster.advance_to(clock.now_ms)
    outcome2 = cycle(sched, store)
    assert len(outcome2.matched) == 1


def test_intra_cycle_port_collision_avoided():
    """Two port jobs matched to the same node in ONE cycle get disjoint
    ports (the mask admits both; the post-solve assigner must not
    double-book)."""
    clock, store, cluster, sched = setup([
        MockHost(node_id="h", hostname="h", mem=8000, cpus=32,
                 ports=((31000, 31003),)),
    ])
    jobs = [make_job(mem=100, cpus=1,
                     resources=Resources(mem=100, cpus=1, ports=2))
            for _ in range(2)]
    store.submit_jobs(jobs)
    outcome = cycle(sched, store)
    assert len(outcome.matched) == 2
    all_ports = [p for rt in cluster.running.values() for p in rt.spec.ports]
    assert len(all_ports) == len(set(all_ports)) == 4
