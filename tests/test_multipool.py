"""Multi-pool batched matching: one device call for all pools, optional
mesh sharding; parity with per-pool matching; gpu-pool dru mode."""
import numpy as np

import jax

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import DruMode, JobState, Pool
from cook_tpu.models.store import JobStore
from cook_tpu.parallel.mesh import make_mesh
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.matcher import MatchConfig
from tests.conftest import FakeClock, make_job


def setup_multi(n_pools=4, hosts_per_pool=3, chunk=0):
    clock = FakeClock()
    store = JobStore(clock=clock)
    hosts = []
    for p in range(n_pools):
        store.set_pool(Pool(name=f"pool{p}"))
        for i in range(hosts_per_pool):
            hosts.append(MockHost(node_id=f"p{p}h{i}", hostname=f"p{p}h{i}",
                                  mem=4000, cpus=8, pool=f"pool{p}"))
    cluster = MockCluster("mock", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster],
                          SchedulerConfig(match=MatchConfig(chunk=chunk)))
    return clock, store, cluster, scheduler


def submit_work(store, n_pools, jobs_per_pool=5):
    jobs = []
    for p in range(n_pools):
        for i in range(jobs_per_pool):
            job = make_job(user=f"u{i % 3}", pool=f"pool{p}", mem=500, cpus=1)
            jobs.append(job)
    store.submit_jobs(jobs)
    return jobs


def test_batched_matches_all_pools():
    clock, store, cluster, scheduler = setup_multi()
    jobs = submit_work(store, 4)
    outcomes = scheduler.match_cycle_all_pools()
    assert set(outcomes) == {f"pool{p}" for p in range(4)}
    total_matched = sum(len(o.matched) for o in outcomes.values())
    assert total_matched == len(jobs)
    for job in jobs:
        assert store.jobs[job.uuid].state == JobState.RUNNING
        [inst] = store.job_instances(job.uuid)
        # placed on a host of the job's own pool
        assert inst.hostname.startswith(f"p{job.pool[-1]}")


def test_batched_equals_per_pool_decisions():
    c1, s1, cl1, sched1 = setup_multi()
    c2, s2, cl2, sched2 = setup_multi()
    for store in (s1, s2):
        rng_jobs = []
        for p in range(4):
            for i in range(6):
                rng_jobs.append(
                    make_job(user=f"u{i % 2}", pool=f"pool{p}",
                             mem=100 * (i + 1), cpus=1))
        # deterministic uuids across the two stores
        for k, job in enumerate(rng_jobs):
            rng_jobs[k] = job.with_(uuid=f"job-{p}-{k}")
        store.submit_jobs(rng_jobs)
    batched = sched1.match_cycle_all_pools()
    per_pool = {
        p.name: sched2.match_cycle(p) for p in s2.pools.values()
    }
    for name in batched:
        b = {(j.uuid, o.hostname) for j, o in batched[name].matched}
        s = {(j.uuid, o.hostname) for j, o in per_pool[name].matched}
        assert b == s


def test_batched_with_mesh_sharding():
    clock, store, cluster, scheduler = setup_multi(n_pools=8)
    jobs = submit_work(store, 8, jobs_per_pool=3)
    mesh = make_mesh()  # 8 virtual cpu devices
    outcomes = scheduler.match_cycle_all_pools(mesh=mesh)
    total = sum(len(o.matched) for o in outcomes.values())
    assert total == len(jobs)


def test_gpu_pool_dru_mode_end_to_end():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="gpu", dru_mode=DruMode.GPU))
    hosts = [MockHost(node_id=f"g{i}", hostname=f"g{i}", mem=8000, cpus=16,
                      gpus=4.0, pool="gpu") for i in range(2)]
    cluster = MockCluster("mock", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster])
    jobs = [make_job(user="a", pool="gpu", mem=100, cpus=1, gpus=2.0)
            for _ in range(3)]
    jobs += [make_job(user="b", pool="gpu", mem=100, cpus=1, gpus=2.0)]
    store.submit_jobs(jobs)
    pool = store.pools["gpu"]
    queue = scheduler.rank_cycle(pool)
    # gpu dru mode: b's first job (cum 2/div) ranks before a's 2nd/3rd
    order_users = [j.user for j in queue.jobs]
    assert order_users[0] in ("a", "b")
    assert "b" in order_users[:2]
    outcome = scheduler.match_cycle(pool)
    # 4 jobs x 2 gpus over 2 hosts x 4 gpus: all fit
    assert len(outcome.matched) == 4
    # gpu jobs only land on gpu hosts (they did; now verify accounting)
    offers = cluster.pending_offers("gpu")
    assert all(o.gpus == 0 for o in offers)


def test_balanced_group_placement():
    """`balanced` host placement bounds the per-attribute-value skew
    within a cycle (constraints.clj:600)."""
    from cook_tpu.models.entities import (
        Group,
        GroupPlacementType,
        HostPlacement,
    )

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    hosts = []
    for rack, names in [("r1", ["a1", "a2"]), ("r2", ["b1", "b2"])]:
        for name in names:
            hosts.append(MockHost(node_id=name, hostname=name, mem=8000,
                                  cpus=32, attributes=(("rack", rack),)))
    cluster = MockCluster("m", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster])
    group = Group(
        uuid="bal",
        host_placement=HostPlacement(type=GroupPlacementType.BALANCED,
                                     attribute="rack", minimum=1),
    )
    jobs = [make_job(group_uuid="bal", mem=100, cpus=1) for _ in range(6)]
    store.submit_jobs(jobs, [group])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    racks = {}
    for j, offer in outcome.matched:
        rack = dict(offer.attributes)["rack"]
        racks[rack] = racks.get(rack, 0) + 1
    assert racks and max(racks.values()) - min(racks.values()) <= 1


def test_balanced_counts_running_members_on_absent_hosts():
    """A RUNNING group member on a host that emits no offer this cycle
    still seeds the balanced-host skew counts (constraints.clj:600 counts
    all running members, not just intra-cycle placements)."""
    from cook_tpu.models.entities import (
        Group,
        GroupPlacementType,
        HostPlacement,
    )

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    hosts = [
        MockHost(node_id="gone1", hostname="gone1", mem=1000, cpus=4,
                 attributes=(("rack", "r1"),)),
        MockHost(node_id="a1", hostname="a1", mem=8000, cpus=32,
                 attributes=(("rack", "r1"),)),
        MockHost(node_id="b1", hostname="b1", mem=8000, cpus=32,
                 attributes=(("rack", "r2"),)),
    ]
    cluster = MockCluster("m", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster])
    pool = store.pools["default"]
    # one empty cycle caches gone1's attributes off its offer
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    assert "gone1" in scheduler.host_attr_cache

    group = Group(
        uuid="bal2",
        host_placement=HostPlacement(type=GroupPlacementType.BALANCED,
                                     attribute="rack", minimum=2),
    )
    j0 = make_job(group_uuid="bal2", mem=100, cpus=1)
    store.submit_jobs([j0], [group])
    store.create_instance(j0.uuid, "t-gone", hostname="gone1",
                          node_id="gone1", compute_cluster="m")
    # the host disappears: full/cordoned hosts emit no offers
    del cluster.hosts["gone1"]

    jobs = [make_job(group_uuid="bal2", mem=100, cpus=1) for _ in range(2)]
    store.submit_jobs(jobs)
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    # with {r1: 1} seeded and minimum=2 distinct values unmet, r1 (a1) is
    # closed to the group until r2 catches up — placements go to b1 only
    assert outcome.matched
    for _, offer in outcome.matched:
        assert dict(offer.attributes)["rack"] == "r2"


def test_balanced_leveling_reopens_closed_value_same_cycle():
    """Intra-cycle leveling re-opens a value the pre-mask closed: with
    running counts {r1: 2, r2: 1} the mask closes r1, but once this
    cycle's first placement levels r2 to 2, a second member may land on
    r1 — the reference's sequential evaluation allows it
    (constraints.clj:600), so the post-solve top-up must recover it."""
    from cook_tpu.models.entities import (
        Group,
        GroupPlacementType,
        HostPlacement,
    )

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    hosts = [
        MockHost(node_id="ra", hostname="ra", mem=1000, cpus=4,
                 attributes=(("rack", "r1"),)),
        MockHost(node_id="rb", hostname="rb", mem=1000, cpus=4,
                 attributes=(("rack", "r2"),)),
        MockHost(node_id="a1", hostname="a1", mem=8000, cpus=32,
                 attributes=(("rack", "r1"),)),
        # room for exactly one 500-mem member this cycle
        MockHost(node_id="b1", hostname="b1", mem=600, cpus=32,
                 attributes=(("rack", "r2"),)),
    ]
    cluster = MockCluster("m", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster])
    pool = store.pools["default"]
    # one empty cycle caches ra/rb attributes off their offers
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)

    group = Group(
        uuid="lvl",
        host_placement=HostPlacement(type=GroupPlacementType.BALANCED,
                                     attribute="rack", minimum=1),
    )
    running = [make_job(group_uuid="lvl", mem=100, cpus=1)
               for _ in range(3)]
    store.submit_jobs(running, [group])
    for job, host in zip(running, ("ra", "ra", "rb")):
        store.create_instance(job.uuid, f"t-{job.uuid[:6]}", hostname=host,
                              node_id=host, compute_cluster="m")
    # the seeded hosts disappear (full hosts emit no offers)
    del cluster.hosts["ra"]
    del cluster.hosts["rb"]

    jobs = [make_job(group_uuid="lvl", mem=500, cpus=1) for _ in range(2)]
    store.submit_jobs(jobs)
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    # both place: one levels r2 via b1, the other takes the re-opened r1
    placed = {dict(o.attributes)["rack"] for _, o in outcome.matched}
    assert len(outcome.matched) == 2
    assert placed == {"r1", "r2"}


def test_simulator_multipool_batched():
    """Multi-pool trace through the simulator with the batched device call:
    every pool's jobs complete, decisions match the per-pool path."""
    from cook_tpu.sim.simulator import SimConfig, Simulator, synth_trace

    all_jobs, all_hosts = [], []
    for p in range(2):
        jobs, hosts = synth_trace(
            60, 6, n_users=4, seed=20 + p, mean_runtime_ms=60_000,
            submit_span_ms=120_000, pool=f"pool{p}")
        # uuids must be unique across pools
        for j in jobs:
            j.uuid = f"p{p}-{j.uuid}"
        for h in hosts:
            h.node_id = f"p{p}-{h.node_id}"
            h.hostname = h.node_id
        all_jobs += jobs
        all_hosts += hosts
    pools = (("pool0", "default"), ("pool1", "default"))
    r_batched = Simulator(all_jobs, all_hosts,
                          SimConfig(cycle_ms=15_000, pools=pools,
                                    batched_match=True)).run()
    r_perpool = Simulator(all_jobs, all_hosts,
                          SimConfig(cycle_ms=15_000, pools=pools,
                                    batched_match=False)).run()
    sig = lambda r: sorted((row["job_uuid"], row["start_ms"], row["host"])
                           for row in r.rows)
    assert sig(r_batched) == sig(r_perpool)
    assert all(row["status"] == "success" for row in r_batched.rows)
