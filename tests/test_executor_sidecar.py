"""Executor + sidecar tests (reference: executor/tests, sidecar tests)."""
import asyncio
import threading
import time

import pytest
import requests

from cook_tpu.executor.runner import ExecutorConfig, TaskRunner, TaskUpdate
from cook_tpu.sidecar.fileserver import FileServer


class Sink:
    def __init__(self):
        self.updates = []

    def __call__(self, u: TaskUpdate):
        self.updates.append(u)

    def of_kind(self, kind):
        return [u for u in self.updates if u.kind == kind]


def test_executor_success(tmp_path):
    sink = Sink()
    runner = TaskRunner(
        "t1", "echo out1 && echo err1 >&2 && exit 0", sink,
        ExecutorConfig(sandbox_dir=str(tmp_path / "sb")),
    )
    code = runner.run()
    assert code == 0
    statuses = [u.status for u in sink.of_kind("status")]
    assert statuses == ["running", "success"]
    [exit_update] = sink.of_kind("exit-code")
    assert exit_update.exit_code == 0
    assert (tmp_path / "sb" / "stdout").read_text() == "out1\n"
    assert (tmp_path / "sb" / "stderr").read_text() == "err1\n"
    [sandbox] = sink.of_kind("sandbox")
    assert sandbox.sandbox.endswith("sb")


def test_executor_failure_exit_code(tmp_path):
    sink = Sink()
    runner = TaskRunner("t2", "exit 3", sink,
                        ExecutorConfig(sandbox_dir=str(tmp_path)))
    assert runner.run() == 3
    assert sink.of_kind("status")[-1].status == "failed"
    assert sink.of_kind("exit-code")[0].exit_code == 3


def test_executor_progress_scraping(tmp_path):
    sink = Sink()
    runner = TaskRunner(
        "t3",
        "echo 'progress: 25 quarter done'; echo 'progress: 50 half'; "
        "echo not progress; echo 'progress: 100'",
        sink,
        ExecutorConfig(sandbox_dir=str(tmp_path),
                       progress_sample_interval_s=0.0),
    )
    runner.run()
    progresses = [(u.progress, u.progress_message)
                  for u in sink.of_kind("progress")]
    assert (25, "quarter done") in progresses
    assert progresses[-1][0] == 100
    # monotone
    values = [p for p, _ in progresses]
    assert values == sorted(values)


def test_executor_kill(tmp_path):
    sink = Sink()
    runner = TaskRunner("t4", "sleep 30", sink,
                        ExecutorConfig(sandbox_dir=str(tmp_path),
                                       shutdown_grace_s=0.2))
    t = threading.Thread(target=runner.run)
    t.start()
    for _ in range(100):
        if runner.proc is not None:
            break
        time.sleep(0.01)
    runner.kill()
    t.join(timeout=5)
    assert not t.is_alive()
    assert sink.of_kind("status")[-1].status == "failed"


@pytest.fixture
def fileserver(tmp_path):
    (tmp_path / "stdout").write_text("hello sandbox\n" * 10)
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "data.txt").write_text("nested")
    server = FileServer(str(tmp_path))
    # run aiohttp app on a thread
    from cook_tpu.rest.server import free_port

    port = free_port()
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)
        from aiohttp import web

        runner = web.AppRunner(server.build_app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", port)
        loop.run_until_complete(site.start())
        started.set()
        loop.run_forever()

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    assert started.wait(5)
    yield f"http://127.0.0.1:{port}", tmp_path
    loop.call_soon_threadsafe(loop.stop)


def test_fileserver_browse_read_download(fileserver):
    url, tmp_path = fileserver
    entries = requests.get(f"{url}/files/browse").json()
    names = [e["path"].rsplit("/", 1)[-1] for e in entries]
    assert "stdout" in names and "sub" in names
    # read with offset paging
    r = requests.get(f"{url}/files/read",
                     params={"path": "stdout", "offset": 6, "length": 7}).json()
    assert r["data"] == "sandbox"
    # offset=-1 returns the size (tail seeks with this)
    r = requests.get(f"{url}/files/read",
                     params={"path": "stdout", "offset": -1}).json()
    assert r["offset"] == len("hello sandbox\n") * 10
    # download
    r = requests.get(f"{url}/files/download", params={"path": "sub/data.txt"})
    assert r.text == "nested"


def test_fileserver_blocks_traversal(fileserver):
    url, _ = fileserver
    r = requests.get(f"{url}/files/read", params={"path": "../../etc/passwd"})
    assert r.status_code == 404
    r = requests.get(f"{url}/files/read", params={"path": "/etc/passwd"})
    assert r.status_code == 404


def test_fileserver_blocks_symlink_escape(fileserver):
    """A task-planted symlink pointing outside the sandbox must not be
    readable through the file server (advisor finding r1: abspath-based
    containment follows symlinks)."""
    url, tmp_path = fileserver
    import os

    os.symlink("/etc/passwd", tmp_path / "sneaky")
    os.symlink("/etc", tmp_path / "sneakydir")
    for path in ("sneaky", "sneakydir/passwd"):
        r = requests.get(f"{url}/files/read", params={"path": path})
        assert r.status_code == 404, path
        r = requests.get(f"{url}/files/download", params={"path": path})
        assert r.status_code == 404, path
    # a symlink that stays inside the sandbox still works
    os.symlink(tmp_path / "stdout", tmp_path / "inlink")
    r = requests.get(f"{url}/files/read",
                     params={"path": "inlink", "offset": 0, "length": 5})
    assert r.json()["data"] == "hello"
