"""POST /pool-move under pressure: quotas/shares with running
instances (previously-untested edge), and capacity deltas racing a
pool-move (the ISSUE-4 satellite).

The pool mover only moves WAITING jobs, but the interesting behavior is
what the move MEANS while the user is already running work: quota
admission and DRU shares are per-(user, pool), so a moved job is judged
against the destination pool's quota/share given the user's running
usage THERE — and the elastic capacity plane shifting pool capacity
mid-move must never wedge either pipeline.
"""
import pytest
import requests

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import (
    InstanceStatus,
    Pool,
    Quota,
    Resources,
    Share,
)
from cook_tpu.models.store import JobStore
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.elastic import ElasticParams
from cook_tpu.txn import TransactionLog
from tests.conftest import FakeClock, make_job

ADMIN = {"X-Cook-Requesting-User": "admin"}


@pytest.fixture()
def rig():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="alpha"))
    store.set_pool(Pool(name="beta"))
    cluster = MockCluster("m", [
        MockHost(node_id="a0", hostname="a0", mem=16000, cpus=16,
                 pool="alpha"),
        MockHost(node_id="b0", hostname="b0", mem=16000, cpus=16,
                 pool="beta"),
    ], clock=clock)
    txn = TransactionLog(store)
    scheduler = Scheduler(store, [cluster],
                          SchedulerConfig(
                              elastic=ElasticParams(enabled=True)),
                          txn=txn)
    api = CookApi(store, scheduler, ApiConfig(admins=("admin",)), txn=txn)
    srv = ServerThread(api).start()
    srv.clock = clock
    srv.store = store
    srv.scheduler = scheduler
    srv.cluster = cluster
    yield srv
    srv.stop()


def _run_instance(store, job, host="b0"):
    store.create_instance(job.uuid, f"task-{job.uuid[:8]}", hostname=host,
                          node_id=host, compute_cluster="m")
    store.update_instance_state(f"task-{job.uuid[:8]}",
                                InstanceStatus.RUNNING, None)


def test_pool_move_respects_destination_quota_with_running_usage(rig):
    """alice already runs 12 cpus in beta under a 14-cpu quota; a moved
    4-cpu job must be quota-capped OUT of beta's queue (while it was
    admissible in alpha), and the running work is untouched."""
    store = rig.store
    store.set_quota(Quota(user="alice", pool="beta",
                          resources=Resources(mem=1e9, cpus=14.0,
                                              gpus=1e9, disk=1e9)))
    running = make_job(user="alice", pool="beta", mem=1000, cpus=12)
    store.submit_jobs([running])
    _run_instance(store, running)
    waiting = make_job(user="alice", pool="alpha", mem=1000, cpus=4)
    store.submit_jobs([waiting])
    # admissible where it is
    queue_alpha = rig.scheduler.rank_cycle(store.pools["alpha"])
    assert any(j.uuid == waiting.uuid for j in queue_alpha.jobs)

    r = requests.post(f"{rig.url}/pool-move",
                      json={"job": waiting.uuid, "pool": "beta"},
                      headers=ADMIN)
    assert r.status_code == 201 and r.json()["moved"] == [waiting.uuid]
    assert store.jobs[waiting.uuid].pool == "beta"
    # destination quota (12 running + 4 > 14) caps it out of the queue
    queue_beta = rig.scheduler.rank_cycle(store.pools["beta"])
    assert waiting.uuid in queue_beta.capped
    assert not any(j.uuid == waiting.uuid for j in queue_beta.jobs)
    # the running instance is untouched by the move
    assert store.jobs[running.uuid].state.value == "running"


def test_pool_move_running_job_is_skipped_not_mangled(rig):
    store = rig.store
    job = make_job(user="alice", pool="alpha", mem=100, cpus=1)
    store.submit_jobs([job])
    _run_instance(store, job, host="a0")
    r = requests.post(f"{rig.url}/pool-move",
                      json={"job": job.uuid, "pool": "beta"},
                      headers=ADMIN)
    assert r.status_code == 201
    assert r.json()["skipped"] == [job.uuid]
    assert store.jobs[job.uuid].pool == "alpha"
    assert store.jobs[job.uuid].state.value == "running"


def test_pool_move_dru_uses_destination_share(rig):
    """Shares are per-(user, pool): after the move, the job's queue DRU
    is computed against the DESTINATION pool's share (tight share in
    beta -> higher dru than alpha's)."""
    store = rig.store
    store.set_share(Share(user="alice", pool="alpha",
                          resources=Resources(mem=1e6, cpus=1e6)))
    store.set_share(Share(user="alice", pool="beta",
                          resources=Resources(mem=10.0, cpus=1.0)))
    job = make_job(user="alice", pool="alpha", mem=100, cpus=2)
    store.submit_jobs([job])
    dru_alpha = rig.scheduler.rank_cycle(
        store.pools["alpha"]).dru[job.uuid]
    r = requests.post(f"{rig.url}/pool-move",
                      json={"job": job.uuid, "pool": "beta"},
                      headers=ADMIN)
    assert r.status_code == 201
    dru_beta = rig.scheduler.rank_cycle(store.pools["beta"]).dru[job.uuid]
    assert dru_beta > dru_alpha


def test_capacity_delta_races_pool_move_over_rest(rig):
    """An elastic plan loaning alpha -> beta lands BETWEEN a job's
    submission to beta and its pool-move to alpha: both commits go
    through the txn pipeline, the queue/ledger stay consistent, and the
    moved job schedules in alpha against alpha's REMAINING (shaved)
    capacity."""
    store = rig.store
    # beta starves -> the planner loans alpha's idle capacity over
    for _ in range(5):
        store.submit_jobs([make_job(user="carol", pool="beta",
                                    mem=4000, cpus=4)])
    record = rig.scheduler.elastic_cycle()
    assert record is not None and record.moves
    loaned = store.capacity_ledger[("alpha", "beta")]["cpus"]
    assert loaned > 0

    # race: admin moves one of the queued beta jobs back into alpha
    target = next(iter(store.pending_jobs("beta")))
    r = requests.post(f"{rig.url}/pool-move",
                      json={"job": target.uuid, "pool": "alpha"},
                      headers=ADMIN)
    assert r.status_code == 201 and r.json()["moved"] == [target.uuid]

    # ledger unchanged by the job move; alpha's offers still shaved
    assert store.capacity_ledger[("alpha", "beta")]["cpus"] == loaned
    alpha_spare = sum(o.cpus for o in rig.cluster.pending_offers("alpha"))
    assert alpha_spare == pytest.approx(16.0 - loaned)
    # the moved job matches in alpha iff the remaining capacity holds it
    rig.scheduler.rank_cycle(store.pools["alpha"])
    outcome = rig.scheduler.match_cycle(store.pools["alpha"])
    if alpha_spare >= 4.0:
        assert any(j.uuid == target.uuid for j, _ in outcome.matched)
    # /debug/elastic reflects the race outcome coherently
    body = requests.get(f"{rig.url}/debug/elastic", headers=ADMIN).json()
    assert body["ledger"][0]["from"] == "alpha"
    assert store.jobs[target.uuid].pool == "alpha"
