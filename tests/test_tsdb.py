"""cook_tpu/obs/tsdb.py — the durable multi-resolution metrics history:
sampling semantics (gauge value / counter rate / histogram quantiles),
rollup correctness vs direct aggregation, retention bounds, segment
recovery across restart, and the /debug/history REST surface."""
import json
import math
import os

import pytest

from cook_tpu.obs.tsdb import (HistoryConfig, MetricsHistory, _Rollup,
                               series_base)
from cook_tpu.utils.metrics import Registry


def make_history(tmp_path=None, **cfg_kw):
    reg = Registry()
    t = {"now": 1_000_000.0}
    cfg = HistoryConfig(**{"sample_s": 1.0, **cfg_kw})
    history = MetricsHistory(
        reg, dir=(str(tmp_path) if tmp_path is not None else None),
        config=cfg, clock=lambda: t["now"])
    return reg, history, t


def tick(history, t, advance_s=10.0):
    history.sample_once()
    t["now"] += advance_s


# ------------------------------------------------------------- sampling


def test_gauge_samples_value_per_label_set():
    reg, history, t = make_history()
    g = reg.gauge("x.g", "h")
    g.set(1.0, {"pool": "a"})
    g.set(2.0, {"pool": "b"})
    tick(history, t)
    q = history.query("x.g")
    assert q["series"]["x.g{pool=a}"] == [[1_000_000.0, 1.0]]
    assert q["series"]["x.g{pool=b}"] == [[1_000_000.0, 2.0]]


def test_counter_samples_rate_not_value():
    reg, history, t = make_history()
    c = reg.counter("x.c", "h")
    c.inc(5)
    tick(history, t)                 # primes; no rate point yet
    assert history.query("x.c.rate")["series"].get("x.c.rate", []) == []
    c.inc(30)
    tick(history, t)                 # 30 over 10s -> 3/s
    points = history.query("x.c.rate")["series"]["x.c.rate"]
    assert points == [[1_000_010.0, 3.0]]


def test_counter_reset_reads_as_zero_rate_not_negative():
    reg, history, t = make_history()
    c = reg.counter("x.c", "h")
    c.inc(100)
    tick(history, t)
    with c._lock:
        c._values[()] = 10.0  # simulated process restart / reset
    tick(history, t)
    points = history.query("x.c.rate")["series"]["x.c.rate"]
    assert points[-1][1] == 0.0


def test_histogram_samples_windowed_p50_p99():
    reg, history, t = make_history()
    h = reg.histogram("x.h", "h", buckets=(0.1, 1.0, 10.0))
    for _ in range(99):
        h.observe(0.05)
    h.observe(5.0)
    tick(history, t)                 # primes
    for _ in range(99):
        h.observe(0.5)
    h.observe(5.0)
    tick(history, t)
    # the second tick's WINDOW is 99x0.5 + 1x5.0: p50 lands in the 1.0
    # bucket, p99 still under 1.0 (99/100 <= rank), p99 edge is 1.0
    p50 = history.query("x.h.p50")["series"]["x.h.p50"]
    p99 = history.query("x.h.p99")["series"]["x.h.p99"]
    assert p50[-1][1] == 1.0
    assert p99[-1][1] == 1.0
    # no observations in the window -> no point (the series goes quiet,
    # it does not repeat stale quantiles)
    tick(history, t)
    assert len(history.query("x.h.p50")["series"]["x.h.p50"]) == 1


# ------------------------------------------------------ rollup correctness


def test_rollup_equals_direct_aggregation_of_raw():
    """The property the satellite pins: every 1m bucket's
    min/max/mean/last/count equals aggregating the raw points that fall
    in its window."""
    reg, history, t = make_history()
    g = reg.gauge("x.g", "h")
    values = [(i * 7 + 3) % 13 - 6 for i in range(181)]
    for v in values:
        g.set(float(v))
        tick(history, t)
    raw = history.query("x.g")["series"]["x.g"]
    assert len(raw) == len(values)
    for step, width in (("1m", 60.0), ("10m", 600.0)):
        buckets = history.query("x.g", step=step)["series"]["x.g"]
        # direct aggregation of the raw stream
        expected: dict[float, list] = {}
        for pt_t, pt_v in raw:
            start = math.floor(pt_t / width) * width
            expected.setdefault(start, []).append(pt_v)
        assert [b["t"] for b in buckets] == sorted(expected)
        for bucket in buckets:
            window = expected[bucket["t"]]
            assert bucket["min"] == min(window)
            assert bucket["max"] == max(window)
            assert bucket["last"] == window[-1]
            assert bucket["count"] == len(window)
            assert bucket["mean"] == pytest.approx(
                sum(window) / len(window))


def test_open_bucket_is_served_before_it_finalizes():
    rollup = _Rollup(60.0, cap=8)
    rollup.add(30.0, 5.0)
    points = rollup.points(since=0.0)
    assert len(points) == 1 and points[0]["count"] == 1


# ------------------------------------------------------------- retention


def test_raw_ring_cap_drops_oldest_never_newest():
    reg, history, t = make_history(raw_points=50)
    g = reg.gauge("x.g", "h")
    for i in range(120):
        g.set(float(i))
        tick(history, t, advance_s=1.0)
    points = history.query("x.g")["series"]["x.g"]
    assert len(points) == 50
    # the newest 50 survived; everything dropped is strictly older
    assert points[-1][1] == 119.0
    assert points[0][1] == 70.0


def test_rollup_retention_never_drops_a_bucket_newer_than_the_cap():
    reg, history, t = make_history(rollup_points=5)
    g = reg.gauge("x.g", "h")
    n_minutes = 12
    for i in range(n_minutes * 6):   # one point per 10s
        g.set(float(i))
        tick(history, t)
    buckets = history.query("x.g", step="1m")["series"]["x.g"]
    # ring cap 5 finalized + the open bucket; strictly the NEWEST ones
    assert len(buckets) == 6
    starts = [b["t"] for b in buckets]
    assert starts == sorted(starts)
    newest_expected = math.floor((t["now"] - 10.0) / 60.0) * 60.0
    assert starts[-1] == newest_expected
    assert starts[-1] - starts[0] == 5 * 60.0


def test_removed_label_set_series_ages_out():
    """A churned label set (per-user gauge removed, per-peer gauge
    cleared) must not keep its series — rings, index row, and the
    counter/histogram prev-state — forever."""
    reg, history, t = make_history(series_ttl_s=100.0)
    g = reg.gauge("x.g", "h")
    c = reg.counter("x.c", "h")
    g.set(1.0, {"user": "bob"})
    c.inc(3, {"user": "bob"})
    tick(history, t)
    tick(history, t)
    assert "x.g{user=bob}" in history.series_index()
    assert history._prev_counts
    g.remove({"user": "bob"})
    with c._lock:
        c._values.clear()
    # the series stops producing; past the TTL it leaves the index,
    # and the prev-state pruned immediately (the label set is gone)
    for _ in range(12):
        tick(history, t)            # 10s ticks; TTL 100s
    assert "x.g{user=bob}" not in history.series_index()
    assert "x.c.rate{user=bob}" not in history._prev_counts


def test_series_ttl_zero_disables_aging():
    reg, history, t = make_history(series_ttl_s=0.0)
    g = reg.gauge("x.g", "h")
    g.set(1.0, {"user": "bob"})
    tick(history, t)
    g.remove({"user": "bob"})
    reg.gauge("x.other", "h").set(1.0)
    for _ in range(30):
        tick(history, t, advance_s=1000.0)
    assert "x.g{user=bob}" in history.series_index()


# ------------------------------------------------------------ durability


def test_segments_rotate_and_retention_prunes_oldest(tmp_path):
    reg, history, t = make_history(tmp_path, segment_lines=10,
                                   max_segments=3)
    g = reg.gauge("x.g", "h")
    for i in range(55):
        g.set(float(i))
        tick(history, t, advance_s=1.0)
    history.stop()
    names = sorted(os.listdir(tmp_path))
    assert len(names) == 3
    assert names[-1] == "segment-000005.jsonl"


def test_recovery_serves_pre_restart_samples(tmp_path):
    reg, history, t = make_history(tmp_path, segment_lines=10,
                                   max_segments=8)
    g = reg.gauge("x.g", "h")
    for i in range(25):
        g.set(float(i))
        tick(history, t, advance_s=1.0)
    history.stop()
    # a new process: fresh history over the same dir
    reg2 = Registry()
    recovered = MetricsHistory(reg2, dir=str(tmp_path),
                               config=HistoryConfig(sample_s=1.0),
                               clock=lambda: t["now"])
    points = recovered.query("x.g")["series"]["x.g"]
    assert len(points) == 25
    assert points[0][1] == 0.0 and points[-1][1] == 24.0
    # rollups rebuilt too, not just raw
    buckets = recovered.query("x.g", step="1m")["series"]["x.g"]
    assert sum(b["count"] for b in buckets) == 25
    # new samples append after the recovered ones, and segment
    # numbering continues instead of clobbering retained files
    g2 = reg2.gauge("x.g", "h")
    g2.set(99.0)
    recovered.sample_once()
    assert recovered.query("x.g")["series"]["x.g"][-1][1] == 99.0
    recovered.stop()


def test_recovery_skips_torn_trailing_line(tmp_path):
    reg, history, t = make_history(tmp_path)
    g = reg.gauge("x.g", "h")
    for i in range(3):
        g.set(float(i))
        tick(history, t)
    history.stop()
    seg = sorted(tmp_path.iterdir())[0]
    with open(seg, "a") as f:
        f.write('{"t": 123, "p": {"x.g":')  # crash mid-append
    recovered = MetricsHistory(Registry(), dir=str(tmp_path),
                               config=HistoryConfig(),
                               clock=lambda: t["now"])
    assert len(recovered.query("x.g")["series"]["x.g"]) == 3
    recovered.stop()


# ------------------------------------------------------------ query shape


def test_query_matches_exact_base_and_prefix():
    reg, history, t = make_history()
    g = reg.gauge("a.one", "h")
    g2 = reg.gauge("a.two", "h")
    g.set(1.0, {"pool": "p"})
    g2.set(2.0)
    tick(history, t)
    assert list(history.query("a.one")["series"]) == ["a.one{pool=p}"]
    assert list(history.query("a.one{pool=p}")["series"]) \
        == ["a.one{pool=p}"]
    assert list(history.query("a.*")["series"]) \
        == ["a.one{pool=p}", "a.two"]
    assert history.query("a.nope")["series"] == {}


def test_query_since_relative_and_bad_step():
    reg, history, t = make_history()
    g = reg.gauge("x.g", "h")
    for i in range(10):
        g.set(float(i))
        tick(history, t)
    recent = history.query("x.g", since=-25.0)["series"]["x.g"]
    assert [v for _, v in recent] == [8.0, 9.0]
    with pytest.raises(ValueError):
        history.query("x.g", step="5m")


def test_series_base_strips_labels():
    assert series_base("a.b{pool=p}") == "a.b"
    assert series_base("a.b") == "a.b"


def test_incident_slice_keeps_only_key_series_window():
    reg, history, t = make_history(key_series=("x.keep",),
                                   incident_window_s=30.0)
    keep = reg.gauge("x.keep", "h")
    drop = reg.gauge("x.drop", "h")
    for i in range(10):
        keep.set(float(i), {"pool": "p"})
        drop.set(float(i))
        tick(history, t, advance_s=10.0)
    bundle_slice = history.incident_slice()
    assert list(bundle_slice["series"]) == ["x.keep{pool=p}"]
    # only the configured window, not the whole ring
    assert len(bundle_slice["series"]["x.keep{pool=p}"]) == 2


# ------------------------------------------------------------ REST surface


def test_debug_history_endpoint_serves_index_series_and_rollups():
    import requests

    from cook_tpu.rest.server import InprocessControlPlane

    plane = InprocessControlPlane(history_sample_s=0)  # manual ticks
    plane.server.start()
    try:
        url = plane.url
        hdr = {"X-Cook-Requesting-User": "admin"}
        requests.post(f"{url}/jobs", json={"jobs": [
            {"command": "true", "mem": 64, "cpus": 0.5}]},
            headers=hdr, timeout=10).raise_for_status()
        plane.history.sample_once()
        plane.history.sample_once()
        index = requests.get(f"{url}/debug/history", headers=hdr,
                             timeout=10).json()
        assert index["enabled"] and index["series"]
        body = requests.get(
            f"{url}/debug/history",
            params={"metric": "jobs_submitted.rate"},
            headers=hdr, timeout=10).json()
        assert body["series"]["jobs_submitted.rate"]
        rolled = requests.get(
            f"{url}/debug/history",
            params={"metric": "rest.in_flight", "step": "1m"},
            headers=hdr, timeout=10).json()
        assert all("mean" in b for pts in rolled["series"].values()
                   for b in pts)
        bad = requests.get(f"{url}/debug/history",
                           params={"metric": "x", "step": "5m"},
                           headers=hdr, timeout=10)
        assert bad.status_code == 400
    finally:
        plane.stop()


def test_incident_bundles_embed_history_slice():
    from cook_tpu.models.entities import Pool
    from cook_tpu.models.store import JobStore
    from cook_tpu.rest.api import ApiConfig, CookApi
    from cook_tpu.utils.metrics import global_registry

    store = JobStore()
    store.set_pool(Pool(name="default"))
    api = CookApi(store, None, ApiConfig())
    global_registry.gauge(
        "obs.health.degraded",
        "1 while /debug/health reports any degradation reason").set(0.0)
    api.history.sample_once()
    api.history.sample_once()
    bundle = api.incidents.capture(
        {"healthy": False, "reasons": ["test"]}, trigger="manual")
    assert "history" in bundle
    assert bundle["history"]["series"].get("obs.health.degraded")
    # the bundle round-trips through JSON (it persists to disk)
    json.dumps(bundle, default=str)
