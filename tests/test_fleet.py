"""cook_tpu/obs/fleet.py — fleet federation: peer polling, the
peer-unreachable / peer-degraded reasons, federated incident capture
with flap suppression, the /debug/fleet surface, and the live-style
leader + follower drill the acceptance criteria pin (fault on the
follower -> leader fleet verdict + federated incident referencing the
peer's own bundle + embedded pre-incident history -> leader restart
still serves the pre-restart history)."""
import json
import time

import pytest
import requests

from cook_tpu import faults
from cook_tpu.obs.fleet import (PEER_DEGRADED, PEER_UNREACHABLE,
                                FleetObservatory, parse_headline)
from cook_tpu.obs.incident import IncidentRecorder

ADMIN = {"X-Cook-Requesting-User": "admin"}


class FakePeers:
    """Injectable transport: a dict of url -> {path: body | Exception}."""

    def __init__(self, peers: dict):
        self.peers = peers

    def fetch(self, url: str, timeout_s: float):
        for base, routes in self.peers.items():
            if url.startswith(base):
                path = url[len(base):]
                body = routes.get(path, Exception(f"404 {path}"))
                if isinstance(body, Exception):
                    raise body
                return body
        raise OSError(f"connection refused: {url}")


def healthy_routes(reasons=()):
    return {
        "/debug/health": {
            "healthy": not reasons,
            "status": "ok" if not reasons else "degraded",
            "reasons": list(reasons),
            "checks": {"contention": {"commit_ack": {"p99_ms": 1.0},
                                      "journal": {},
                                      "endpoints": {}}},
        },
        "/debug/replica": {"shards": {"0": {"staleness_ms": 40.0}}},
        "/metrics": "cook_obs_health_degraded 0.0\n"
                    "cook_rest_in_flight 2.0\n",
        "/debug/incidents": {"incidents": [{"id": "inc-000007"}]},
    }


def make_fleet(peers: dict, **kw):
    fake = FakePeers(peers)
    kw.setdefault("incidents", IncidentRecorder())
    fleet = FleetObservatory(self_url="http://leader",
                             peers=tuple(peers),
                             fetch_fn=fake.fetch, **kw)
    return fleet, fake


# --------------------------------------------------------------- polling


def test_healthy_peer_row_carries_staleness_and_headline():
    fleet, _ = make_fleet({"http://peer-a": healthy_routes()})
    rows = fleet.poll_once()
    row = rows["http://peer-a"]
    assert row["ok"] and row["healthy"] and row["status"] == "ok"
    assert row["staleness"] == {"0": 40.0}
    assert row["headline"]["rest.in_flight"] == 2.0
    assert "commit_ack" in row["contention"]
    verdict = fleet.verdict()
    assert verdict["healthy"] and verdict["reasons"] == []
    assert verdict["worst_shard"] == {"node": "http://peer-a",
                                      "shard": "0", "staleness_ms": 40.0}


def test_dead_peer_becomes_unreachable_within_one_poll():
    fleet, _ = make_fleet({"http://gone": {}})  # every fetch raises
    fleet.poll_once()
    verdict = fleet.verdict()
    assert verdict["status"] == "degraded"
    assert verdict["reasons"] == [PEER_UNREACHABLE]
    [row] = [n for n in verdict["nodes"] if not n.get("self")]
    assert not row["ok"] and "error" in row
    assert row["poll_age_s"] >= 0.0


def test_degraded_peer_attaches_its_own_reasons():
    fleet, fake = make_fleet(
        {"http://peer-a": healthy_routes(["fsync-stall"])})
    fleet.poll_once()
    verdict = fleet.verdict()
    assert verdict["reasons"] == [PEER_DEGRADED]
    [row] = [n for n in verdict["nodes"] if not n.get("self")]
    assert row["reasons"] == ["fsync-stall"]


def test_recovery_clears_the_reason_and_stamps_the_bundle():
    fake_routes = healthy_routes(["fsync-stall"])
    fleet, fake = make_fleet({"http://peer-a": fake_routes})
    fleet.poll_once()
    bundle = fleet._peer_state["http://peer-a"]["bundle"]
    assert bundle is not None and bundle["recovered_time"] is None
    fake.peers["http://peer-a"] = healthy_routes()
    fleet.poll_once()
    assert fleet.verdict()["healthy"]
    assert bundle["recovered_time"] is not None


def test_federated_incident_references_the_peer_bundle():
    incidents = IncidentRecorder()
    fleet, _ = make_fleet(
        {"http://peer-a": healthy_routes(["quality-drift"])},
        incidents=incidents)
    fleet.poll_once()
    [summary] = incidents.bundles()
    assert summary["trigger"] == "fleet-peer"
    bundle = incidents.get(summary["id"])
    [degradation] = bundle["verdict"]["degradations"]
    assert degradation["reason"] == PEER_DEGRADED
    assert degradation["peer"] == "http://peer-a"
    assert degradation["peer_reasons"] == ["quality-drift"]
    assert degradation["peer_incident_id"] == "inc-000007"
    json.dumps(bundle, default=str)  # bundle persists; must round-trip


def test_flapping_peer_is_cooldown_suppressed_then_deferred():
    incidents = IncidentRecorder()
    routes = healthy_routes(["fsync-stall"])
    fleet, fake = make_fleet({"http://peer-a": routes},
                             incidents=incidents, cooldown_s=3600.0)
    fleet.poll_once()                       # edge 1: captures
    assert len(incidents.bundles()) == 1
    for _ in range(3):                      # flap inside the cooldown
        fake.peers["http://peer-a"] = healthy_routes()
        fleet.poll_once()
        fake.peers["http://peer-a"] = routes
        fleet.poll_once()
    assert len(incidents.bundles()) == 1    # suppressed, not flooded
    state = fleet._peer_state["http://peer-a"]
    assert state["pending"]                 # ... but deferred, not lost
    state["last_capture"] = float("-inf")   # cooldown clears
    fleet.poll_once()
    assert len(incidents.bundles()) == 2


def test_unreachable_peer_capture_skips_the_bundle_reference():
    incidents = IncidentRecorder()
    fleet, _ = make_fleet({"http://gone": {}}, incidents=incidents)
    fleet.poll_once()
    [summary] = incidents.bundles()
    bundle = incidents.get(summary["id"])
    [degradation] = bundle["verdict"]["degradations"]
    assert degradation["reason"] == PEER_UNREACHABLE
    assert degradation["peer_incident_id"] is None


def test_peers_fn_registry_merges_and_excludes_self():
    fleet, _ = make_fleet(
        {"http://peer-a": healthy_routes()},
        peers_fn=lambda: ["http://leader", "http://peer-a/",
                          "http://peer-b"])
    assert fleet.peer_list() == ["http://peer-a", "http://peer-b"]


def test_crashed_peer_stays_unreachable_after_registry_prunes_it():
    """Peers are sticky: the dynamic registry half is the replication
    ack table, which liveness-prunes a crashed standby within seconds —
    the dead node must KEEP its peer-unreachable row, not vanish and
    flip the fleet verdict back to ok."""
    registry = {"urls": ["http://standby"]}
    fleet, fake = make_fleet({"http://standby": healthy_routes()},
                             peers_fn=lambda: registry["urls"])
    fleet.peers = ()  # registry-only registration, the no-config path
    fleet.poll_once()
    assert fleet.verdict()["healthy"]
    # the standby crashes AND its acks age out of the registry
    fake.peers.pop("http://standby")
    registry["urls"] = []
    fleet.poll_once()
    verdict = fleet.verdict()
    assert verdict["reasons"] == [PEER_UNREACHABLE]
    assert "http://standby" in [n["url"] for n in verdict["nodes"]]
    # explicit decommission is the way a peer actually leaves
    fleet.forget_peer("http://standby")
    fleet.poll_once()
    assert fleet.verdict()["healthy"]
    assert fleet.peer_list() == []


def test_parse_headline_takes_worst_label_and_skips_histograms():
    text = ("# HELP cook_rank_queue_len x\n"
            "cook_rank_queue_len{pool=\"a\"} 3.0\n"
            "cook_rank_queue_len{pool=\"b\"} 9.0\n"
            "cook_obs_health_degraded 1.0\n"
            "cook_job_latency_end_to_end_bucket{le=\"1\"} 4\n"
            "garbage line\n")
    out = parse_headline(text, ("rank.queue_len", "obs.health.degraded",
                                "job.latency.end_to_end"))
    assert out == {"rank.queue_len": 9.0, "obs.health.degraded": 1.0}


# --------------------------------------------------------- live-style drill


def test_drill_leader_follower_fault_fleet_incident_history(tmp_path):
    """The acceptance drill: boot a leader + one follower control
    plane, arm a fault on the follower -> the leader's /debug/fleet
    shows the peer degraded (its own reasons attached) within one poll
    interval, the leader's incident ring gains a federated entry
    referencing the peer's bundle, the bundle embeds a non-empty
    pre-incident history slice; restart the leader and /debug/history
    still serves the pre-restart samples."""
    from cook_tpu.obs.contention import ContentionParams
    from cook_tpu.obs.tsdb import HistoryConfig, MetricsHistory
    from cook_tpu.rest.api import ApiConfig
    from cook_tpu.rest.server import InprocessControlPlane

    follower_dir = tmp_path / "follower"
    follower_dir.mkdir()
    follower = InprocessControlPlane(
        config=ApiConfig(contention=ContentionParams(fsync_stall_s=0.05)),
        history_sample_s=0,
        data_dir=str(follower_dir)).start()
    leader_history_dir = str(tmp_path / "leader-metrics")
    leader = InprocessControlPlane(history_sample_s=0).start()
    leader.api.history = MetricsHistory(
        dir=leader_history_dir, config=HistoryConfig(sample_s=0))
    leader.api.incidents.add_collector(
        "history", leader.api.history.incident_slice)
    fleet = FleetObservatory(
        self_url=leader.url, peers=(follower.url,), poll_s=0.2,
        incidents=leader.api.incidents,
        self_verdict_fn=leader.api.health_verdict)
    leader.api.fleet = fleet
    try:
        # pre-incident history on the leader: the health rollup gauge is
        # a key series, so sampling now gives the bundle its slice
        leader.api.health_verdict()
        leader.api.history.sample_once()
        time.sleep(0.05)
        leader.api.history.sample_once()

        # baseline: the follower is a healthy peer
        fleet.poll_once()
        assert leader.api.fleet.verdict()["healthy"]

        # arm the fault ON THE FOLLOWER's write path and trip it: a
        # 100 ms fsync stall against a 50 ms bound degrades its health
        faults.arm(faults.FaultSchedule([faults.FaultRule(
            point=faults.JOURNAL_FSYNC, mode="delay", delay_s=0.1)]))
        try:
            r = requests.post(
                f"{follower.url}/jobs",
                json={"jobs": [{"command": "true", "mem": 64,
                                "cpus": 0.5}]},
                headers=ADMIN, timeout=30)
            assert r.status_code == 201
        finally:
            faults.disarm()

        # within ONE poll interval the leader sees the degradation
        fleet.start()
        deadline = time.monotonic() + 5.0
        verdict = None
        while time.monotonic() < deadline:
            verdict = leader.api.fleet.verdict()
            if PEER_DEGRADED in verdict["reasons"]:
                break
            time.sleep(0.05)
        fleet.stop()
        assert verdict is not None \
            and PEER_DEGRADED in verdict["reasons"], verdict
        [row] = [n for n in verdict["nodes"] if not n.get("self")]
        assert "fsync-stall" in row["reasons"]
        assert row["poll_age_s"] < 5.0

        # the leader's incident ring gained a federated entry that
        # references the PEER's own bundle (the follower captured one
        # when its health was polled)
        federated = [b for b in leader.api.incidents.bundles()
                     if b["trigger"] == "fleet-peer"]
        assert federated, leader.api.incidents.bundles()
        bundle = leader.api.incidents.get(federated[-1]["id"])
        [degradation] = bundle["verdict"]["degradations"]
        assert degradation["peer"] == follower.url
        assert "fsync-stall" in degradation["peer_reasons"]
        peer_incident_id = degradation["peer_incident_id"]
        assert peer_incident_id is not None
        peer_index = requests.get(f"{follower.url}/debug/incidents",
                                  headers=ADMIN, timeout=10).json()
        assert peer_incident_id in [b["id"]
                                    for b in peer_index["incidents"]]

        # ... and embeds a non-empty pre-incident history slice
        assert bundle["history"]["series"], bundle["history"]

        # GET /debug/fleet serves the same verdict over HTTP
        over_http = requests.get(f"{leader.url}/debug/fleet",
                                 headers=ADMIN, timeout=10).json()
        assert over_http["enabled"]
        assert PEER_DEGRADED in over_http["reasons"]

        # "restart" the leader: a fresh history over the same dir still
        # serves the pre-restart samples
        pre_restart = leader.api.history.query("obs.health.degraded")
        assert pre_restart["series"]["obs.health.degraded"]
        leader.api.history.stop()
        reborn = MetricsHistory(dir=leader_history_dir,
                                config=HistoryConfig(sample_s=0))
        recovered = reborn.query("obs.health.degraded")
        assert recovered["series"]["obs.health.degraded"] \
            == pre_restart["series"]["obs.health.degraded"]
        reborn.stop()
    finally:
        fleet.stop()
        leader.stop()
        follower.stop()
