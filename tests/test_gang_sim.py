"""Gang scheduling A/B through the trace simulator (ISSUE 19
acceptance): on the gang/topology trace, turning the gang machinery on
must improve BOTH gang assembly wait and placement fragmentation vs
naive flat placement — and never partially place a gang."""
from __future__ import annotations

import pytest

from cook_tpu.scheduler.core import SchedulerConfig
from cook_tpu.scheduler.matcher import MatchConfig
from cook_tpu.sim.loadgen import gang_topology_trace
from cook_tpu.sim.simulator import SimConfig, Simulator

BLOCK_HOSTS = 4


def _run(jobs, hosts, *, gang_enabled: bool):
    match = MatchConfig(
        gang_enabled=gang_enabled,
        topology_block_hosts=BLOCK_HOSTS,
        topology_weight=0.5 if gang_enabled else 0.0,
    )
    cfg = SimConfig(
        cycle_ms=30_000,
        max_cycles=60,
        scheduler=SchedulerConfig(match=match),
    )
    return Simulator(jobs, hosts, cfg).run()


@pytest.fixture(scope="module")
def ab():
    jobs, hosts = gang_topology_trace(block_hosts=BLOCK_HOSTS)
    naive_run = _run(jobs, hosts, gang_enabled=False)
    gang_run = _run(jobs, hosts, gang_enabled=True)
    return {
        "jobs": jobs,
        "hosts": hosts,
        "naive_run": naive_run,
        "gang_run": gang_run,
        "naive": naive_run.gang_stats(jobs, hosts,
                                      nodes_per_block=BLOCK_HOSTS),
        "gang": gang_run.gang_stats(jobs, hosts,
                                    nodes_per_block=BLOCK_HOSTS),
    }


def test_every_gang_completes_both_modes(ab):
    for mode in ("naive", "gang"):
        for g in ab[mode]["per_gang"]:
            assert g["placed_members"] == g["size"], (mode, g)


def test_gang_mode_assembles_more_gangs(ab):
    assert ab["gang"]["assembled"] == ab["gang"]["gangs"]
    assert ab["gang"]["assembled"] > ab["naive"]["assembled"]


def test_gang_wait_improves(ab):
    assert ab["gang"]["wait_ms_p50"] < ab["naive"]["wait_ms_p50"]


def test_fragmentation_improves(ab):
    # the one-block rule: every assembled gang is contiguous
    assert ab["gang"]["mean_block_spread"] == 1.0
    assert ab["gang"]["mean_block_spread"] \
        < ab["naive"]["mean_block_spread"]


def test_gang_mode_never_partially_places(ab):
    """Cycle-granular all-or-nothing: any cycle that launches members
    of a gang launches the ENTIRE gang."""
    sizes = {}
    for tj in ab["jobs"]:
        if tj.gang:
            sizes[tj.gang] = sizes.get(tj.gang, 0) + 1
    launched_by_cycle = {}
    for rec in ab["gang_run"].cycle_records:
        members = [m["job"] for m in rec.get("matched", [])
                   if m["job"].startswith("gang")]
        if members:
            launched_by_cycle[rec["cycle"]] = members
    assert launched_by_cycle, "gangs never launched"
    for cycle, members in launched_by_cycle.items():
        per_gang = {}
        for m in members:
            gang = "gang-" + m.split("-")[0][len("gang"):]
            per_gang.setdefault(gang, []).append(m)
        for gang, ms in per_gang.items():
            assert len(ms) == sizes[gang], (cycle, gang, ms)


def test_gang_cycle_records_track_skips(ab):
    recs = [r for r in ab["gang_run"].cycle_records
            if r.get("gangs_considered")]
    assert recs, "no gang cycle records"
    blocked = [r for r in recs if r.get("gangs_blocked")]
    assert blocked, "trace never made a gang wait"
    reasons = set()
    for r in blocked:
        reasons.update(r.get("gang_block_reasons", {}))
    assert "no-block-capacity" in reasons
    # the skip detail renders the best-block shortfall for operators
    details = [s["detail"] for r in blocked
               for s in r.get("skipped", [])
               if s.get("code") == "gang-incomplete"]
    assert any("hosts free" in d for d in details)
    # naive run has gang handling off: no gang record fields populated
    assert not any(r.get("gangs_considered")
                   for r in ab["naive_run"].cycle_records)


def test_scalar_churn_not_starved_by_gang_mode(ab):
    """The scalar top-up: stripped gangs hand hosts back, so gang mode
    does not stretch the run for the non-gang workload."""
    assert ab["gang_run"].virtual_ms <= ab["naive_run"].virtual_ms
