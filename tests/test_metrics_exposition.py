"""Prometheus exposition correctness: label escaping, cumulative
histogram buckets, +Inf, and render-under-write safety."""
import math
import threading

import pytest

from cook_tpu.utils.metrics import (
    Histogram,
    Registry,
    _escape_label_value,
    _fmt_labels,
)


def test_label_value_escaping():
    assert _escape_label_value('say "hi"') == 'say \\"hi\\"'
    assert _escape_label_value("a\\b") == "a\\\\b"
    assert _escape_label_value("line1\nline2") == "line1\\nline2"
    # non-string values stringify before escaping
    assert _escape_label_value(7) == "7"


def test_fmt_labels_escapes_into_exposition():
    rendered = _fmt_labels((("cmd", 'echo "x\\y"\n'),))
    assert rendered == '{cmd="echo \\"x\\\\y\\"\\n"}'


def test_escaped_labels_render_one_line_each():
    reg = Registry()
    reg.counter("evil").inc(1.0, {"reason": 'oom "killer"\nretry'})
    text = reg.render_prometheus()
    [line] = [l for l in text.splitlines() if l.startswith("cook_evil{")]
    assert '\\"killer\\"' in line and "\\n" in line
    # the raw newline/quote never reach the output unescaped
    assert "\n" not in line


def test_histogram_cumulative_buckets_and_inf():
    reg = Registry()
    h = reg.histogram("lat", buckets=(0.1, 1.0, math.inf))
    for v in (0.05, 0.5, 0.7, 5.0):
        h.observe(v)
    text = reg.render_prometheus()
    lines = [l for l in text.splitlines() if l.startswith("cook_lat")]
    assert 'cook_lat_bucket{le="0.1"} 1' in lines
    assert 'cook_lat_bucket{le="1.0"} 3' in lines
    assert 'cook_lat_bucket{le="+Inf"} 4' in lines
    assert "cook_lat_count 4" in lines
    assert "cook_lat_sum 6.25" in lines


def test_histogram_without_inf_bucket_still_counts_everything():
    # a bucket list missing +Inf silently dropped large observations
    # before; the constructor now appends it
    h = Histogram("x", buckets=(1.0, 2.0))
    assert h.buckets[-1] == math.inf
    h.observe(100.0)
    assert h.count() == 1


def test_histogram_labeled_series_render_independently():
    reg = Registry()
    h = reg.histogram("per_pool", buckets=(1.0, math.inf))
    h.observe(0.5, {"pool": "a"})
    h.observe(5.0, {"pool": "b"})
    text = reg.render_prometheus()
    assert 'cook_per_pool_bucket{pool="a",le="1.0"} 1' in text
    assert 'cook_per_pool_bucket{pool="b",le="1.0"} 0' in text
    assert 'cook_per_pool_bucket{pool="b",le="+Inf"} 1' in text
    assert 'cook_per_pool_count{pool="a"} 1' in text


def test_help_lines_rendered_and_escaped():
    reg = Registry()
    reg.gauge("g", "multi\nline help")
    reg.gauge("g").set(1.0)
    text = reg.render_prometheus()
    assert "# HELP cook_g multi\\nline help" in text
    assert "# TYPE cook_g gauge" in text


def test_render_concurrent_with_writes_never_corrupts():
    reg = Registry()
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            reg.counter("c").inc(1.0, {"k": f"v{i % 7}"})
            reg.histogram("h").observe(0.01 * (i % 30))
            i += 1

    def reader():
        try:
            for _ in range(200):
                text = reg.render_prometheus()
                for line in text.splitlines():
                    if line and not line.startswith("#"):
                        # every sample line must parse: name{...} value
                        name, _, value = line.rpartition(" ")
                        assert name
                        float(value)
        except Exception as e:  # noqa: BLE001 — surfaced in the main thread
            errors.append(e)

    threads = [threading.Thread(target=writer) for _ in range(2)]
    r = threading.Thread(target=reader)
    for t in threads:
        t.start()
    r.start()
    r.join()
    stop.set()
    for t in threads:
        t.join()
    assert not errors


def test_registry_type_conflict_still_raises():
    reg = Registry()
    reg.counter("dup")
    with pytest.raises(TypeError):
        reg.gauge("dup")
