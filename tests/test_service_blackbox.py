"""Blackbox service test: boot `python -m cook_tpu` as a subprocess with a
mock cluster config, drive it purely over HTTP/CLI, watch a job run to
completion on real (wall-clock) trigger loops."""
import json
import os
import signal
import subprocess
import sys
import time

import pytest
import requests

from cook_tpu.rest.server import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def service(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("svc")
    port = free_port()
    config = {
        "port": port,
        "pools": [{"name": "default"}],
        "rank_interval_s": 0.2,
        "match_interval_s": 0.2,
        "rebalancer_interval_s": 3600,
        "clusters": [{
            "kind": "mock",
            "name": "local",
            "default_runtime_ms": 500,
            "hosts": [{"node_id": "h0", "mem": 8000, "cpus": 16},
                      {"node_id": "h1", "mem": 8000, "cpus": 16}],
        }],
    }
    cfg = tmp / "config.json"
    cfg.write_text(json.dumps(config))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
           "PYTHONPATH": REPO}
    proc = subprocess.Popen(
        [sys.executable, "-m", "cook_tpu", "--config", str(cfg)],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    url = f"http://127.0.0.1:{port}"
    try:
        for _ in range(300):
            try:
                if requests.get(f"{url}/debug", timeout=1).ok:
                    break
            except requests.ConnectionError:
                time.sleep(0.2)
        else:
            raise RuntimeError("service did not come up")
        yield url
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_job_runs_via_real_service(service):
    url = service
    h = {"X-Cook-Requesting-User": "bb"}
    r = requests.post(f"{url}/jobs", json={"jobs": [
        {"command": "blackbox", "mem": 100, "cpus": 1,
         "expected_runtime": 1}
    ]}, headers=h)
    assert r.status_code == 201, r.text
    uuid = r.json()["jobs"][0]
    # real trigger loops pick it up within a few hundred ms; the mock
    # cluster completes it when wall-clock passes its runtime
    deadline = time.time() + 30
    status = None
    while time.time() < deadline:
        status = requests.get(f"{url}/jobs/{uuid}", headers=h).json()["status"]
        if status == "completed":
            break
        time.sleep(0.3)
    assert status == "completed", status
    # metrics endpoint reflects the work
    metrics = requests.get(f"{url}/metrics", headers=h).text
    assert "cook_jobs_submitted" in metrics


def test_service_runs_tuned_matcher_config(service):
    """The deployed service must run the hardware-tuned chunked kernel
    (tuned_match.json), not the exact-kernel chunk=0 fallback — the
    VERDICT r2 'perf trap' regression check."""
    h = {"X-Cook-Requesting-User": "bb"}
    settings = requests.get(f"{service}/settings", headers=h).json()
    matcher = settings["matcher"]
    with open(os.path.join(REPO, "tuned_match.json")) as f:
        tuned = json.load(f)
    assert matcher["chunk"] == tuned["chunk"] > 0
    assert matcher["backend"] == tuned["backend"]
    assert matcher["rounds"] == tuned["rounds"]
    assert matcher["passes"] == tuned["passes"]
    assert matcher["kc"] == tuned["kc"]
    assert matcher["quality_audit_every"] > 0


def test_cli_against_real_service(service, tmp_path, capsys):
    from cook_tpu.client.cli import main as cli_main

    cfg = tmp_path / "cs.json"
    cfg.write_text(json.dumps(
        {"clusters": [{"name": "svc", "url": service}]}))
    rc = cli_main(["--config", str(cfg), "--user", "bb",
                   "submit", "--mem", "64", "cli job"])
    assert rc == 0
    uuid = capsys.readouterr().out.strip()
    rc = cli_main(["--config", str(cfg), "--user", "bb",
                   "wait", uuid, "--timeout", "30"])
    assert rc == 0
