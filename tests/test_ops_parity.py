"""Parity tests: the JAX kernels against the reference-faithful CPU
implementations, over randomized problems (the role of the reference's
dru/scheduler/rebalancer unit suites, SURVEY §4.1)."""
import numpy as np
import pytest

import jax.numpy as jnp

from cook_tpu.ops import cpu_reference as ref
from cook_tpu.ops.common import BIG, pad_to
from cook_tpu.ops.dru import DruTasks, dru_rank
from cook_tpu.ops.match import MatchProblem, chunked_match, greedy_match
from cook_tpu.ops.rebalance import RebalanceState, find_preemption_decision


def random_dru_problem(rng, t=200, u=13):
    user = rng.integers(0, u, size=t)
    mem = rng.uniform(1, 100, size=t)
    cpus = rng.uniform(0.1, 8, size=t)
    gpus = rng.integers(0, 3, size=t).astype(float)
    order_key = rng.permutation(t).astype(np.float64)
    mem_div = rng.uniform(100, 1000, size=u)
    cpu_div = rng.uniform(1, 50, size=u)
    gpu_div = rng.uniform(1, 8, size=u)
    return user, mem, cpus, gpus, order_key, mem_div, cpu_div, gpu_div


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("gpu_mode", [False, True])
def test_dru_parity(seed, gpu_mode):
    rng = np.random.default_rng(seed)
    user, mem, cpus, gpus, order_key, mem_div, cpu_div, gpu_div = (
        random_dru_problem(rng)
    )
    want_dru, want_order = ref.ref_dru_order(
        user, mem, cpus, gpus, order_key, mem_div, cpu_div, gpu_div,
        gpu_mode=gpu_mode,
    )
    # pad to a bucket with invalid tail
    t, pad_t = len(user), 256
    tasks = DruTasks(
        user=jnp.asarray(pad_to(user.astype(np.int32), pad_t)),
        mem=jnp.asarray(pad_to(mem, pad_t)),
        cpus=jnp.asarray(pad_to(cpus, pad_t)),
        gpus=jnp.asarray(pad_to(gpus, pad_t)),
        order_key=jnp.asarray(pad_to(order_key, pad_t, fill=BIG)),
        valid=jnp.asarray(pad_to(np.ones(t, dtype=bool), pad_t, fill=False)),
    )
    got = dru_rank(
        tasks,
        jnp.asarray(mem_div),
        jnp.asarray(cpu_div),
        jnp.asarray(gpu_div),
        gpu_mode=gpu_mode,
    )
    np.testing.assert_allclose(np.asarray(got.dru[:t]), want_dru, rtol=1e-4)
    # padding scores BIG and ranks last
    assert np.all(np.asarray(got.dru[t:]) >= BIG)
    assert np.all(np.asarray(got.rank[:t]) < t)
    # order parity: equal-dru ties may permute across users, so compare the
    # sequence of dru values along the order, and exact within-user order.
    got_order = np.asarray(got.order[:t])
    np.testing.assert_allclose(
        want_dru[got_order], want_dru[want_order], rtol=1e-4
    )
    for uu in range(13):
        mine = [i for i in got_order if user[i] == uu]
        theirs = [i for i in want_order if user[i] == uu]
        assert mine == theirs


def random_match_problem(rng, j=150, n=40):
    demands = np.stack(
        [
            rng.uniform(10, 500, size=j),
            rng.uniform(0.5, 8, size=j),
            (rng.uniform(0, 1, size=j) < 0.1) * rng.integers(1, 4, size=j),
        ],
        axis=-1,
    )
    totals = np.stack(
        [rng.uniform(1000, 8000, size=n), rng.uniform(8, 64, size=n)], axis=-1
    )
    frac = rng.uniform(0.3, 1.0, size=(n, 1))
    avail = np.concatenate(
        [totals * frac, rng.integers(0, 5, size=(n, 1)).astype(float)], axis=-1
    )
    feasible = rng.uniform(size=(j, n)) > 0.05
    return demands, avail, totals, feasible


@pytest.mark.parametrize("seed", range(5))
def test_greedy_match_exact_parity(seed):
    rng = np.random.default_rng(100 + seed)
    demands, avail, totals, feasible = random_match_problem(rng)
    want = ref.ref_greedy_match(demands, avail, totals, feasible)
    j, n = feasible.shape
    problem = MatchProblem(
        demands=jnp.asarray(demands),
        job_valid=jnp.ones(j, dtype=bool),
        avail=jnp.asarray(avail),
        totals=jnp.asarray(totals),
        node_valid=jnp.ones(n, dtype=bool),
        feasible=jnp.asarray(feasible),
    )
    got = greedy_match(problem)
    np.testing.assert_array_equal(np.asarray(got.assignment), want)
    # availability bookkeeping agrees
    placed = want >= 0
    spent = np.zeros_like(avail)
    for jj in np.where(placed)[0]:
        spent[want[jj]] += demands[jj]
    np.testing.assert_allclose(np.asarray(got.new_avail), avail - spent,
                               rtol=1e-4, atol=1e-3)


def _assert_chunked_parity(demands, avail, totals, feasible, *,
                           chunk=64, bar=0.99, **kwargs):
    """Chunked vs exact greedy: no oversubscription, and >= `bar` of the
    exact packing on jobs placed AND on each resource dimension (the
    project target is >=0.99, BASELINE.json 'Fenzo packing efficiency')."""
    j, n = demands.shape[0], avail.shape[0]
    problem = MatchProblem(
        demands=jnp.asarray(demands),
        job_valid=jnp.ones(j, dtype=bool),
        avail=jnp.asarray(avail),
        totals=jnp.asarray(totals),
        node_valid=jnp.ones(n, dtype=bool),
        feasible=jnp.asarray(feasible) if feasible is not None else None,
    )
    exact = greedy_match(problem)
    fast = chunked_match(problem, chunk=chunk, **kwargs)
    q_exact = ref.packing_quality(demands, np.asarray(exact.assignment))
    q_fast = ref.packing_quality(demands, np.asarray(fast.assignment))
    assert np.all(np.asarray(fast.new_avail) >= -1e-3)
    assert q_fast["num_placed"] >= bar * q_exact["num_placed"]
    assert q_fast["cpus_placed"] >= bar * q_exact["cpus_placed"]
    assert q_fast["mem_placed"] >= bar * q_exact["mem_placed"]


@pytest.mark.parametrize("seed", range(8))
def test_chunked_match_near_parity(seed):
    rng = np.random.default_rng(200 + seed)
    demands, avail, totals, feasible = random_match_problem(rng, j=256, n=64)
    _assert_chunked_parity(demands, avail, totals, feasible)


@pytest.mark.parametrize("seed", range(5))
def test_chunked_match_parity_skewed_demands(seed):
    """Zipf-ish job sizes: a few huge jobs among many tiny ones stress the
    candidate-truncation and prefix-accept paths."""
    rng = np.random.default_rng(400 + seed)
    j, n = 256, 64
    base = rng.choice([16, 64, 256, 1024, 4096], j,
                      p=[0.4, 0.3, 0.15, 0.1, 0.05]).astype(float)
    demands = np.stack([base, np.maximum(base / 256, 0.25), np.zeros(j)],
                       axis=-1)
    totals = np.stack([np.full(n, 8192.0), np.full(n, 32.0)], axis=-1)
    avail = np.concatenate([totals * rng.uniform(0.2, 1.0, (n, 1)),
                            np.zeros((n, 1))], axis=-1)
    _assert_chunked_parity(demands, avail, totals, None)


@pytest.mark.parametrize("seed", range(5))
def test_chunked_match_parity_few_feasible_nodes(seed):
    """Each job feasible on only ~3 nodes (tight constraints): contention
    concentrates on few nodes and candidate lists carry mostly -BIG."""
    rng = np.random.default_rng(500 + seed)
    demands, avail, totals, _ = random_match_problem(rng, j=256, n=64)
    feasible = rng.uniform(size=(256, 64)) < 0.05
    feasible[np.arange(256), rng.integers(0, 64, 256)] = True
    _assert_chunked_parity(demands, avail, totals, feasible)


@pytest.mark.parametrize("seed", range(8))
def test_bucketed_match_near_parity(seed):
    """Bucketed candidate mode (one candidate list per demand class) must
    hold the same >=0.99 packing bar — continuous-uniform demands are the
    hard case (256 distinct demands into <=64 classes)."""
    rng = np.random.default_rng(700 + seed)
    demands, avail, totals, feasible = random_match_problem(rng, j=256, n=64)
    _assert_chunked_parity(demands, avail, totals, feasible,
                           bucketed=True, passes=3)


@pytest.mark.parametrize("seed", range(5))
def test_bucketed_match_parity_skewed_demands(seed):
    """Discrete skewed shapes (the realistic case: few requested sizes) —
    classes are exact, so bucketed candidates lose nothing."""
    rng = np.random.default_rng(800 + seed)
    j, n = 256, 64
    base = rng.choice([16, 64, 256, 1024, 4096], j,
                      p=[0.4, 0.3, 0.15, 0.1, 0.05]).astype(float)
    demands = np.stack([base, np.maximum(base / 256, 0.25), np.zeros(j)],
                       axis=-1)
    totals = np.stack([np.full(n, 8192.0), np.full(n, 32.0)], axis=-1)
    avail = np.concatenate([totals * rng.uniform(0.2, 1.0, (n, 1)),
                            np.zeros((n, 1))], axis=-1)
    _assert_chunked_parity(demands, avail, totals, None,
                           bucketed=True, passes=3)


@pytest.mark.parametrize("seed", range(5))
def test_bucketed_match_parity_few_feasible_nodes(seed):
    """Constraint masks can't be pre-applied to class-shared candidate
    lists; the rounds' [K,kc] mask recheck must keep acceptance exact."""
    rng = np.random.default_rng(900 + seed)
    demands, avail, totals, _ = random_match_problem(rng, j=256, n=64)
    feasible = rng.uniform(size=(256, 64)) < 0.05
    feasible[np.arange(256), rng.integers(0, 64, 256)] = True
    _assert_chunked_parity(demands, avail, totals, feasible,
                           bucketed=True, passes=6)
    # masked assignments must never violate the constraint mask
    problem = MatchProblem(
        demands=jnp.asarray(demands), job_valid=jnp.ones(256, bool),
        avail=jnp.asarray(avail), totals=jnp.asarray(totals),
        node_valid=jnp.ones(64, bool), feasible=jnp.asarray(feasible))
    a = np.asarray(chunked_match(problem, chunk=64, bucketed=True,
                                 passes=6).assignment)
    placed = a >= 0
    assert feasible[np.where(placed)[0], a[placed]].all()


def test_match_respects_validity_masks():
    j, n = 8, 4
    demands = np.tile([100.0, 1.0, 0.0], (j, 1))
    avail = np.tile([1000.0, 10.0, 0.0], (n, 1))
    totals = avail[:, :2].copy()
    problem = MatchProblem(
        demands=jnp.asarray(demands),
        job_valid=jnp.asarray([True] * 4 + [False] * 4),
        avail=jnp.asarray(avail),
        totals=jnp.asarray(totals),
        node_valid=jnp.asarray([True, True, False, False]),
        feasible=None,
    )
    got = greedy_match(problem)
    a = np.asarray(got.assignment)
    assert np.all(a[4:] == -1)          # invalid jobs unplaced
    assert set(a[:4]) <= {0, 1}          # invalid nodes untouched


def random_rebalance_problem(rng, t=300, h=25):
    task_host = rng.integers(0, h, size=t)
    task_dru = rng.uniform(0, 5, size=t)
    task_res = np.stack(
        [
            rng.uniform(10, 500, size=t),
            rng.uniform(0.5, 8, size=t),
            (rng.uniform(size=t) < 0.1) * rng.integers(1, 4, size=t),
        ],
        axis=-1,
    )
    eligible = rng.uniform(size=t) > 0.2
    spare = np.stack(
        [
            rng.uniform(0, 300, size=h),
            rng.uniform(0, 4, size=h),
            np.zeros(h),
        ],
        axis=-1,
    )
    host_ok = rng.uniform(size=h) > 0.1
    return task_host, task_dru, task_res, eligible, spare, host_ok


@pytest.mark.parametrize("seed", range(8))
def test_rebalance_parity(seed):
    rng = np.random.default_rng(300 + seed)
    task_host, task_dru, task_res, eligible, spare, host_ok = (
        random_rebalance_problem(rng)
    )
    demand = (400.0, 6.0, 0.0)
    pending_dru, thresh, mindiff = 0.4, 1.0, 0.5
    want = ref.ref_preemption_decision(
        task_host, task_dru, task_res[:, 0], task_res[:, 1], task_res[:, 2],
        eligible, spare, host_ok, demand, pending_dru, thresh, mindiff,
    )
    state = RebalanceState(
        task_host=jnp.asarray(task_host, dtype=jnp.int32),
        task_dru=jnp.asarray(task_dru),
        task_res=jnp.asarray(task_res),
        task_eligible=jnp.asarray(eligible),
        spare=jnp.asarray(spare),
        host_ok=jnp.asarray(host_ok),
    )
    got = find_preemption_decision(
        state, jnp.asarray(demand), pending_dru, thresh, mindiff
    )
    if want is None:
        assert int(got.host) == -1
        assert not np.any(np.asarray(got.preempt_mask))
        return
    want_host, want_tasks = want
    got_mask = np.asarray(got.preempt_mask)
    if not want_tasks:  # spare-only decision
        assert float(got.score) >= BIG
        assert not got_mask.any()
        # any spare-fitting host is acceptable; check chosen host's spare fits
        ch = int(got.host)
        assert np.all(spare[ch] >= np.asarray(demand))
    else:
        assert int(got.host) == want_host
        assert sorted(np.where(got_mask)[0].tolist()) == sorted(want_tasks)
        np.testing.assert_allclose(
            float(got.score), task_dru[want_tasks[-1]], rtol=1e-6
        )


@pytest.mark.parametrize("seed", range(3))
def test_chunked_match_tight_capacity_efficiency(seed):
    """Capacity-constrained packing (demand >> supply): the chunked matcher
    must stay within 1% of sequential greedy on resources placed — in
    practice it lands ABOVE 1.0, because contention spreading fills
    secondary nodes pure greedy leaves fragmented."""
    rng = np.random.default_rng(700 + seed)
    j, n = 2048, 128
    demands = np.stack([
        rng.choice([512, 1024, 2048, 4096], j).astype(np.float32),
        rng.choice([0.5, 1, 2, 4], j).astype(np.float32),
        np.zeros(j, np.float32)], axis=-1)
    totals = np.stack([np.full(n, 16384.0, np.float32),
                       np.full(n, 16.0, np.float32)], axis=-1)
    avail = np.concatenate(
        [totals * rng.uniform(0.5, 1.0, (n, 1)).astype(np.float32),
         np.zeros((n, 1), np.float32)], axis=-1)
    problem = MatchProblem(jnp.asarray(demands), jnp.ones(j, bool),
                           jnp.asarray(avail), jnp.asarray(totals),
                           jnp.ones(n, bool), None)
    exact = np.asarray(greedy_match(problem).assignment)
    fast_r = chunked_match(problem, chunk=256, rounds=4, kc=64, passes=2)
    fast = np.asarray(fast_r.assignment)
    assert np.all(np.asarray(fast_r.new_avail) >= -1e-3)  # no oversubscribe
    qe = ref.packing_quality(demands, exact)
    qf = ref.packing_quality(demands, fast)
    assert qf["cpus_placed"] >= 0.99 * qe["cpus_placed"]
    assert qf["mem_placed"] >= 0.99 * qe["mem_placed"]


def _xl_problem(j, n, j_real, seed):
    rng = np.random.default_rng(seed)
    demands = np.stack([
        rng.choice([512, 1024, 2048, 4096, 8192], j).astype(np.float32),
        rng.choice([0.5, 1, 2, 4], j).astype(np.float32),
        np.zeros(j, np.float32)], axis=-1)
    totals = np.stack([np.full(n, 65536.0, np.float32),
                       np.full(n, 32.0, np.float32)], axis=-1)
    avail = np.concatenate(
        [totals * rng.uniform(0.2, 1.0, (n, 1)).astype(np.float32),
         np.zeros((n, 1), np.float32)], axis=-1)
    job_valid = np.zeros(j, bool)
    job_valid[:j_real] = True
    problem = MatchProblem(jnp.asarray(demands), jnp.asarray(job_valid),
                           jnp.asarray(avail), jnp.asarray(totals),
                           jnp.ones(n, bool), None)
    return demands, avail, totals, problem


def _assert_chunk_boundary_invariants(demands, avail, totals, problem,
                                      j_real, chunk):
    """The XL verification the satellite asks for: across MANY chunk
    boundaries, the conflict-resolution rounds must never oversubscribe
    a node, new_avail must equal avail minus exactly the placed demand,
    the padded job tail must stay empty, and packing must stay within 2%
    of the flat sequential reference."""
    result = chunked_match(problem, chunk=chunk, rounds=3, kc=64, passes=2)
    a = np.asarray(result.assignment)
    new_avail = np.asarray(result.new_avail)
    assert (a[j_real:] == -1).all(), "padded tail jobs were placed"
    placed = a >= 0
    n = avail.shape[0]
    use = np.zeros((n, 3), np.float64)
    np.add.at(use, a[placed], demands[placed].astype(np.float64))
    over = use - avail[:, :3].astype(np.float64)
    assert over.max() <= 1e-2, f"oversubscribed by {over.max()}"
    drift = np.abs(avail[:, :3].astype(np.float64) - use
                   - new_avail[:, :3].astype(np.float64)).max()
    assert drift <= 1e-2, f"new_avail inconsistent by {drift}"
    flat = ref.np_greedy_match(demands[:j_real], avail[:, :3], totals)
    qf = ref.packing_quality(demands[:j_real], flat)
    qc = ref.packing_quality(demands[:j_real], a[:j_real])
    assert qc["cpus_placed"] >= 0.98 * qf["cpus_placed"]


def test_chunked_match_boundary_invariants_16k():
    """Fast tier of the XL verification (16k jobs x 512 nodes, 16 chunk
    boundaries) — runs in tier-1; the >= 64k tier is the slow test
    below."""
    demands, avail, totals, problem = _xl_problem(16384, 512, 16_000,
                                                  seed=41)
    _assert_chunk_boundary_invariants(demands, avail, totals, problem,
                                      16_000, chunk=1024)


@pytest.mark.slow
def test_chunked_match_boundary_invariants_xl():
    """The satellite's >= 64k-job verification: 65536 jobs x 1024 nodes,
    64 chunk boundaries, checked against the flat reference.  (Run
    explicitly: tier-1 excludes `slow`.)"""
    demands, avail, totals, problem = _xl_problem(65536, 1024, 65_000,
                                                  seed=42)
    _assert_chunk_boundary_invariants(demands, avail, totals, problem,
                                      65_000, chunk=1024)
