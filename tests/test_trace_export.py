"""Chrome-trace export + span-ring concurrency (the trace-export half of
the incident observatory, cook_tpu/utils/tracing.chrome_trace)."""
import json
import threading
import time

from cook_tpu.utils import tracing


def _ring_events(trace):
    """Non-metadata events from a chrome_trace() result."""
    return [e for e in trace["traceEvents"] if e["ph"] not in ("M",)]


def test_chrome_trace_duration_and_instant_events():
    with tracing.span("export_unit_outer", pool="poolx"):
        time.sleep(0.002)
    tracing.record_event("export_unit_marker", follower="f1")
    spans = [s for s in tracing.recent_spans(tracing.ring_capacity())
             if s["name"].startswith("export_unit_")]
    trace = tracing.chrome_trace(spans)
    events = _ring_events(trace)
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)

    outer = by_name["export_unit_outer"]
    # a pool-tagged span renders on BOTH its thread track (pid 1) and
    # the pool track (pid 2)
    assert {e["pid"] for e in outer} == {1, 2}
    for e in outer:
        assert e["ph"] == "X"
        assert e["dur"] >= 2000  # microseconds
        assert e["args"]["pool"] == "poolx"

    [marker] = by_name["export_unit_marker"]
    assert marker["ph"] == "i"
    assert marker["args"]["follower"] == "f1"

    # track metadata names the thread and pool lanes
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    names = {e["args"]["name"] for e in meta}
    assert "pools" in names and "host threads" in names
    assert "pool:poolx" in names
    # the whole object must be JSON-serializable (it IS the REST body
    # and the --trace-out file)
    json.dumps(trace)


def test_chrome_trace_preserves_txn_id():
    with tracing.correlate("txn-export-1"):
        with tracing.span("export_unit_txn"):
            pass
    spans = [s for s in tracing.recent_spans(tracing.ring_capacity())
             if s["name"] == "export_unit_txn"]
    trace = tracing.chrome_trace(spans)
    [event] = [e for e in _ring_events(trace) if e["pid"] == 1]
    assert event["args"]["txn_id"] == "txn-export-1"


def test_ring_entries_carry_thread_identity():
    with tracing.span("export_unit_tid"):
        pass
    [entry] = [s for s in tracing.recent_spans(tracing.ring_capacity())
               if s["name"] == "export_unit_tid"]
    assert entry["tid"] == threading.get_ident()
    assert entry["thread"] == threading.current_thread().name


def test_concurrent_correlate_scopes_stay_thread_local():
    """Each thread's spans must carry ITS correlation id — a cross-thread
    bleed would mislabel /debug/spans?txn_id= and the trace export."""
    n_threads, per_thread = 8, 50
    errors = []

    def worker(i):
        txn = f"txn-conc-{i}"
        with tracing.correlate(txn):
            for _ in range(per_thread):
                with tracing.span("export_unit_conc", worker=i):
                    if tracing.current_correlation() != txn:
                        errors.append(f"thread {i} lost its correlation")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    mine = [s for s in tracing.recent_spans(tracing.ring_capacity())
            if s["name"] == "export_unit_conc"]
    assert len(mine) >= n_threads * per_thread
    # every recorded span's txn tag matches the scope of the worker
    # that opened it — no cross-thread bleed (os thread idents recycle,
    # so the worker tag, not tid, is the identity here)
    for s in mine:
        assert s["tags"]["txn_id"] == f"txn-conc-{s['tags']['worker']}"


def test_chrome_trace_export_while_appending():
    """Export must be safe against a scheduler thread appending spans —
    the 'deque mutated during iteration' class of bug."""
    stop = threading.Event()
    errors = []

    def appender():
        i = 0
        while not stop.is_set():
            with tracing.span("export_unit_append", pool=f"p{i % 3}"):
                pass
            tracing.record_event("export_unit_append_marker")
            i += 1

    def exporter():
        try:
            for _ in range(200):
                trace = tracing.chrome_trace(limit=512)
                json.dumps(trace)
        except Exception as e:  # noqa: BLE001 — the failure under test
            errors.append(e)

    writer = threading.Thread(target=appender)
    writer.start()
    readers = [threading.Thread(target=exporter) for _ in range(3)]
    for r in readers:
        r.start()
    for r in readers:
        r.join()
    stop.set()
    writer.join()
    assert not errors
