"""Fairness observatory (cook_tpu/obs/fairness.py): the seeded
rebalance drill end to end (victim kill -> ledger -> rollups -> tsdb ->
timeline -> cycle record), Jain-drop drift detection landing fairness
evidence in an incident bundle, ledger/label bounds, failover recovery
replay, the preemption-heavy loadgen A/B, and the mp scatter-merge
shape."""
from types import SimpleNamespace

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import (
    DEFAULT_USER,
    InstanceStatus,
    Pool,
    Quota,
    Resources,
    Share,
)
from cook_tpu.models.persistence import attach_journal, recover
from cook_tpu.models.store import JobStore
from cook_tpu.obs.fairness import (
    FAIRNESS_DRIFT,
    FairnessConfig,
    FairnessObservatory,
    jain_index,
)
from cook_tpu.obs.incident import job_timeline
from cook_tpu.obs.tsdb import MetricsHistory
from cook_tpu.scheduler.core import Scheduler
from cook_tpu.utils.metrics import global_registry
from tests.conftest import FakeClock, make_job


# ---------------------------------------------------------------- helpers


def _ledger_entry(i: int, pool_freed_mem: float = 100.0) -> dict:
    return {
        "t_ms": 1000 + i,
        "preemptor_job": f"job-{i}",
        "preemptor_user": "starved",
        "hostname": f"h{i % 4}",
        "min_preempted_dru": 2.0,
        "victims": [{"task_id": f"t-{i}", "user": "hog", "dru": 2.0,
                     "wasted_s": 1.5, "mem": pool_freed_mem, "cpus": 1.0,
                     "gpus": 0.0}],
        "freed": {"mem": pool_freed_mem, "cpus": 1.0, "gpus": 0.0},
    }


class _RankStore:
    """Minimal store surface observe_rank needs: usage + share + quota."""

    def __init__(self, dru_by_user: dict):
        self.dru_by_user = dru_by_user

    def user_usage(self, pool):
        return {u: Resources(mem=d * 100.0, cpus=0.0)
                for u, d in self.dru_by_user.items()}

    def get_share(self, user, pool):
        return Resources(mem=100.0, cpus=float("inf"), gpus=float("inf"))

    def get_quota(self, user, pool):
        return Quota(user=user, pool=pool,
                     resources=Resources(mem=float("inf"),
                                         cpus=float("inf")),
                     count=2**31)


def _rank(obs: FairnessObservatory, pool: str, dru_by_user: dict) -> None:
    queue = SimpleNamespace(jobs=[], dru={})
    obs.observe_rank(pool, queue, _RankStore(dru_by_user))


def _preemption_rig():
    """The debug_smoke recipe: finite default share, a hog filling both
    hosts, then a starved user's job that no longer fits — rebalance
    must transact a victim kill."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m",
        [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4000, cpus=8)
         for i in range(2)],
        clock=clock)
    scheduler = Scheduler(store, [cluster])
    pool = store.pools["default"]
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=500, cpus=4)))
    hogs = [make_job(user="hog", mem=1600, cpus=2) for _ in range(4)]
    store.submit_jobs(hogs)
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    clock.advance(30_000)  # victims accrue runtime -> wasted_s > 0
    store.submit_jobs([make_job(user="starved", mem=1000, cpus=1)])
    scheduler.rank_cycle(pool)
    decisions = scheduler.rebalance_cycle(pool)
    return clock, store, scheduler, pool, decisions


# ---------------------------------------------------------------- unit


def test_jain_index_math():
    assert jain_index([]) == 1.0
    assert jain_index([0.0, 0.0]) == 1.0          # all-zero: vacuously fair
    assert jain_index([2.0, 2.0, 2.0]) == 1.0
    # one dominant user -> 1/n limit
    skewed = jain_index([100.0, 0.001, 0.001, 0.001])
    assert 0.25 <= skewed < 0.3
    # scale invariance
    assert abs(jain_index([1, 2, 3]) - jain_index([10, 20, 30])) < 1e-12


# ------------------------------------------------------- the seeded drill


def test_rebalance_drill_lands_ledger_rollups_and_tsdb():
    clock, store, scheduler, pool, decisions = _preemption_rig()
    assert any(d.task_ids for d in decisions), "drill must preempt"

    snap = scheduler.fairness.snapshot()
    body = snap["pools"]["default"]

    # ledger: preemptor/victim users, DRU at decision, nonzero wasted work
    assert body["ledger"], "transacted preemption must land in the ledger"
    entry = body["ledger"][-1]
    assert entry["preemptor_user"] == "starved"
    assert entry["kind"] == "fairness"
    assert entry["victims"]
    for victim in entry["victims"]:
        assert victim["user"] == "hog"
        assert victim["dru"] > 1.0          # hog was far over share
        assert victim["wasted_s"] == 30.0   # clock advanced 30s post-match
    assert entry["wasted_s"] >= 30.0
    assert entry["freed"]["mem"] > 0

    # rollups + fragmentation
    rollups = body["rollups"]
    assert rollups["preemptions"] >= 1
    assert rollups["tasks_preempted"] >= 1
    assert rollups["wasted_s"]["fairness"] >= 30.0
    assert rollups["by_user"]["starved"]["preemptions_initiated"] >= 1
    assert rollups["by_user"]["hog"]["victim_tasks"] >= 1
    frag = body["fragmentation"]
    assert 0.0 <= frag["fragmentation"] <= 1.0
    assert frag["decisions"] >= 1

    # trajectories sampled at rank time: the hog reads over share
    assert body["trajectories"]["hog"]["dru"] > 1.0
    assert body["trajectories"]["starved"]["queued"] >= 1
    assert 0.0 < body["jain_index"] <= 1.0

    # the victim instance really died with the rebalancer reason
    tid = entry["victims"][0]["task_id"]
    inst = store.instances[tid]
    assert inst.status == InstanceStatus.FAILED
    assert inst.status.terminal

    # victim_detail joins the ledger for the timeline
    detail = scheduler.fairness.victim_detail(tid)
    assert detail is not None
    assert detail["preemptor_user"] == "starved"
    assert detail["runtime_lost_s"] == 30.0

    # fairness.* gauges land in the metrics history (prefix-matched key
    # series, so `cs history fairness.user.dru` can sparkline the drift)
    history = MetricsHistory()  # global registry
    history.sample_once()
    series = history.query("fairness.user.dru")["series"]
    assert any("pool=default" in k and "user=hog" in k for k in series)
    jain_series = history.query("fairness.jain_index")["series"]
    assert any("pool=default" in k for k in jain_series)


def test_drill_enriches_timeline_and_cycle_record():
    clock, store, scheduler, pool, decisions = _preemption_rig()
    tid = next(tid for d in decisions for tid in d.task_ids)
    victim_job = store.jobs[store.instances[tid].job_uuid]

    timeline = job_timeline(store, scheduler.recorder, victim_job,
                            fairness=scheduler.fairness)
    preemptions = [e["preemption"] for e in timeline["events"]
                   if "preemption" in e]
    assert preemptions, "preempted terminal event must carry ledger detail"
    assert preemptions[0]["preemptor_user"] == "starved"
    assert preemptions[0]["runtime_lost_s"] == 30.0
    assert preemptions[0]["dru_at_decision"] > 1.0

    # the rebalance pass's cycle record carries the fairness rollup
    records = scheduler.recorder.records_json(limit=50)
    fair = [r["fairness"] for r in records if r.get("fairness")]
    assert fair and fair[-1]["tasks_preempted"] >= 1
    assert fair[-1]["wasted_s"] >= 30.0


def test_non_rebalancer_mea_culpa_kill_lands_in_mea_culpa_bucket():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m", [MockHost(node_id="h0", hostname="h0", mem=4000, cpus=8)],
        clock=clock)
    scheduler = Scheduler(store, [cluster])
    pool = store.pools["default"]
    job = make_job(user="unlucky")
    store.submit_jobs([job])
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    [tid] = [i.task_id for i in store.job_instances(job.uuid)]
    clock.advance(12_000)
    # the backing cluster killed the node out from under the task: a
    # mea-culpa failure that is NOT a rebalancer preemption
    store.update_instance_state(tid, InstanceStatus.FAILED, "node-removed")

    rollups = scheduler.fairness.snapshot()["pools"]["default"]["rollups"]
    assert rollups["wasted_s"]["mea_culpa"] == 12.0
    assert rollups["wasted_s"]["fairness"] == 0.0
    # no ledger entry — there is no preemptor to attribute
    assert scheduler.fairness.snapshot()["pools"]["default"]["ledger"] == []


# ----------------------------------------------------------------- drift


def test_sustained_jain_drop_raises_drift_and_incident_evidence(store):
    from cook_tpu.rest.api import ApiConfig, CookApi

    api = CookApi(store, None, ApiConfig())
    api.incidents.cooldown_s = 0.0
    pool = "driftpool"

    even = {"a": 1.0, "b": 1.0, "c": 1.0, "d": 1.0}
    skew = {"a": 4.0, "b": 0.1, "c": 0.1, "d": 0.1}
    for _ in range(20):
        _rank(api.fairness, pool, even)
    verdict = api.health_verdict()
    assert FAIRNESS_DRIFT not in verdict["reasons"]

    for _ in range(8):                     # fill the recent window low
        _rank(api.fairness, pool, skew)
    verdict = api.health_verdict()
    assert FAIRNESS_DRIFT in verdict["reasons"]
    assert not verdict["healthy"]
    [deg] = [d for d in verdict["degradations"]
             if d["reason"] == FAIRNESS_DRIFT]
    assert deg["pool"] == pool
    assert deg["recent"] < deg["baseline"]
    assert verdict["checks"]["fairness"][pool]["jain_index"] < 0.5

    # the ok->degraded edge captured a bundle with fairness evidence
    bundles = api.incidents.bundles()
    assert bundles
    bundle = api.incidents.get(bundles[-1]["id"])
    assert FAIRNESS_DRIFT in bundle["reasons"]
    assert bundle["fairness"]["pools"][pool]["jain_index"] < 0.5
    assert "trajectories" in bundle["fairness"]["pools"][pool]

    # recovery: even usage again clears the reason (and the gauge edge)
    for _ in range(8):
        _rank(api.fairness, pool, even)
    verdict = api.health_verdict()
    assert FAIRNESS_DRIFT not in verdict["reasons"]
    assert api.fairness._drift_active is False


# ---------------------------------------------------------------- bounds


def test_ledger_ring_holds_capacity_newest_win():
    obs = FairnessObservatory(FairnessConfig(ledger_capacity=8))
    for i in range(20):
        obs.record_decisions("default", [_ledger_entry(i)])
    body = obs.snapshot(ledger_limit=100)["pools"]["default"]
    assert len(body["ledger"]) == 8
    assert [e["t_ms"] for e in body["ledger"]] == list(range(1012, 1020))
    # rollups keep counting past the ring: totals are not ring-bounded
    assert body["rollups"]["preemptions"] == 20
    assert body["rollups"]["tasks_preempted"] == 20


def test_trajectory_labels_age_out_and_truncate():
    obs = FairnessObservatory(FairnessConfig(max_users_per_pool=2))
    pool = "ageout-pool"
    dru_gauge = global_registry.gauge(
        "fairness.user.dru",
        "per-user running dominant-resource usage over share")

    _rank(obs, pool, {"a": 3.0, "b": 2.0})
    assert dru_gauge.value({"pool": pool, "user": "b"}) == 2.0

    # b departs: its gauge labels must be retracted, not left stale
    _rank(obs, pool, {"a": 3.0})
    assert dru_gauge.value({"pool": pool, "user": "b"}) == 0.0
    assert obs._exported_users[pool] == {"a"}

    # over-cap population keeps the top users by DRU, counts the rest
    _rank(obs, pool, {"a": 3.0, "b": 2.0, "c": 1.0, "d": 0.5})
    body = obs.snapshot()["pools"][pool]
    assert set(body["trajectories"]) == {"a", "b"}
    assert body["trajectories_truncated"] == 2
    assert dru_gauge.value({"pool": pool, "user": "c"}) == 0.0


def test_rollup_user_overflow_collapses_to_other():
    obs = FairnessObservatory(FairnessConfig(max_rollup_users=3))
    for i in range(6):
        entry = _ledger_entry(i)
        entry["victims"][0]["user"] = f"victim{i}"
        obs.record_decisions("default", [entry])
    by_user = obs.snapshot()["pools"]["default"]["rollups"]["by_user"]
    assert len(by_user) <= 4                    # cap + the "(other)" slot
    assert "(other)" in by_user
    assert by_user["(other)"]["victim_tasks"] >= 1


# --------------------------------------------------------------- recovery


def test_rollups_survive_failover_recovery_replay(tmp_path, clock):
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    writer = attach_journal(store, str(tmp_path / "journal.jsonl"))
    j1 = make_job(user="victim")
    j2 = make_job(user="unlucky")
    store.submit_jobs([j1, j2])
    store.create_instance(j1.uuid, "t1", hostname="h1", compute_cluster="c")
    store.update_instance_state("t1", InstanceStatus.RUNNING)
    store.create_instance(j2.uuid, "t2", hostname="h2", compute_cluster="c")
    store.update_instance_state("t2", InstanceStatus.RUNNING)
    clock.advance(45_000)
    store.update_instance_state("t1", InstanceStatus.FAILED, 1002)
    clock.advance(15_000)
    store.update_instance_state("t2", InstanceStatus.FAILED, "node-removed")
    writer.close()

    restored = recover(str(tmp_path), clock=clock)
    obs = FairnessObservatory()
    assert obs.recover(restored) == 2
    rollups = obs.snapshot()["pools"]["default"]["rollups"]
    # rebalancer preemption -> fairness bucket; node loss -> mea-culpa
    assert rollups["tasks_preempted"] == 1
    assert rollups["wasted_s"]["fairness"] == 45.0
    assert rollups["wasted_s"]["mea_culpa"] == 60.0
    assert rollups["by_user"]["victim"]["victim_tasks"] == 1
    assert rollups["by_user"]["unlucky"]["victim_wasted_s"] == 60.0


# ---------------------------------------------------------------- loadgen


def test_preemption_heavy_trace_ab_vs_standard():
    """A/B: the preemption-heavy trace is distinguishable from a
    standard completion-heavy run by BOTH the Jain index (depressed
    while the hog monopolizes next to under-share late users) and the
    wasted-work accounting (nonzero fairness bucket + populated
    ledger); the standard run shows neither."""
    from cook_tpu.sim.loadgen import (completion_heavy_trace,
                                      preemption_heavy_trace)
    from cook_tpu.sim.simulator import SimConfig, Simulator

    def _run(jobs, hosts):
        sim = Simulator(jobs, hosts,
                        SimConfig(cycle_ms=30_000, rebalance_every=1,
                                  max_cycles=60))
        sim.store.set_share(Share(user=DEFAULT_USER, pool="default",
                                  resources=Resources(mem=500.0, cpus=2.0)))
        sim.store.dynamic_config["rebalancer"] = {
            "safe_dru_threshold": 0.0, "min_dru_diff": 0.01,
            "max_preemption": 10}
        result = sim.run()
        jain_samples = list(
            sim.scheduler.fairness._baselines["default"]._samples)
        return result, jain_samples

    heavy, heavy_jain = _run(*preemption_heavy_trace(
        hog_jobs=8, late_jobs=3, hosts=4, runtime_ms=240_000,
        late_arrival_ms=30_000, n_late_users=3))
    std, std_jain = _run(*completion_heavy_trace(
        jobs=8, hosts=4, runtime_ms=60_000, n_users=1))

    heavy_body = heavy.fairness["pools"]["default"]
    std_body = std.fairness["pools"]["default"]

    # wasted work distinguishes the traces
    assert heavy_body["rollups"]["tasks_preempted"] >= 1
    assert heavy_body["rollups"]["wasted_s"]["fairness"] > 0.0
    assert heavy_body["ledger"]
    assert std_body["rollups"]["tasks_preempted"] == 0
    assert std_body["rollups"]["wasted_s"]["fairness"] == 0.0

    # so does the Jain index: the heavy run dips while hog + under-share
    # late users run side by side; the single-user standard run never
    # leaves perfect fairness
    assert min(heavy_jain) < 0.97
    assert min(std_jain) > 0.999


# --------------------------------------------------------------- mp merge


def test_mp_scatter_merge_composes_disjoint_pool_bodies():
    from cook_tpu.mp.router import _merge

    a = FairnessObservatory()
    b = FairnessObservatory()
    a.record_decisions("pool_a", [_ledger_entry(0)])
    _rank(a, "pool_a", {"hog": 2.0, "starved": 0.5})
    b.record_decisions("pool_b", [_ledger_entry(1)])

    merged = _merge(a.snapshot(), b.snapshot())
    assert merged["enabled"] is True           # bool, not summed to 2
    assert set(merged["pools"]) == {"pool_a", "pool_b"}
    # group-owned pools are disjoint: per-pool numbers arrive untouched
    pa = merged["pools"]["pool_a"]
    assert pa["jain_index"] == a.snapshot()["pools"]["pool_a"]["jain_index"]
    assert pa["rollups"]["preemptions"] == 1
    assert merged["pools"]["pool_b"]["rollups"]["preemptions"] == 1
    assert len(pa["ledger"]) == 1
