"""Pipelined match cycle invariants (scheduler/pipeline.py): decision
parity with the serial path, transactions committing in pool order under
overlap, solve/launch failure isolation, the kill-lock honored across
async launches, encode-cache invalidation, and the batched path's
pool-axis padding keeping one XLA program across pool counts."""
import threading
import time

import numpy as np
import pytest

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import (
    InstanceStatus,
    JobState,
    Pool,
    Quota,
    Resources,
)
from cook_tpu.models.reasons import REASONS_BY_NAME
from cook_tpu.models.store import JobStore
from cook_tpu.ops.common import PendingResult
from cook_tpu.scheduler import flight_recorder as flight_codes
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.encode_cache import EncodeCache
from cook_tpu.scheduler.matcher import MatchConfig
from tests.conftest import FakeClock, make_job


def setup_multi(n_pools=4, hosts_per_pool=3, jobs_per_pool=5, chunk=0,
                cluster_cls=MockCluster, **config_kw):
    clock = FakeClock()
    store = JobStore(clock=clock)
    hosts = []
    for p in range(n_pools):
        store.set_pool(Pool(name=f"pool{p}"))
        for i in range(hosts_per_pool):
            hosts.append(MockHost(node_id=f"p{p}h{i}", hostname=f"p{p}h{i}",
                                  mem=4000, cpus=8, pool=f"pool{p}"))
    cluster = cluster_cls("mock", hosts, clock=clock)
    scheduler = Scheduler(
        store, [cluster],
        SchedulerConfig(match=MatchConfig(chunk=chunk), **config_kw))
    jobs = []
    for p in range(n_pools):
        for i in range(jobs_per_pool):
            job = make_job(user=f"u{i % 3}", pool=f"pool{p}",
                           mem=100 * (i % 4 + 1), cpus=1)
            jobs.append(job.with_(uuid=f"job-{p}-{i}"))
    store.submit_jobs(jobs)
    return clock, store, cluster, scheduler, jobs


# ------------------------------------------------------------- the engine


def test_pipelined_matches_all_pools():
    _, store, _, scheduler, jobs = setup_multi()
    outcomes = scheduler.match_cycle_pipelined()
    assert set(outcomes) == {f"pool{p}" for p in range(4)}
    assert sum(len(o.matched) for o in outcomes.values()) == len(jobs)
    for job in jobs:
        # drain_launches is on by default: backend effects are visible
        # when the pass returns, like the serial path
        assert store.jobs[job.uuid].state == JobState.RUNNING
        [inst] = store.job_instances(job.uuid)
        assert inst.hostname.startswith(f"p{job.pool[-1]}")


def test_pipelined_equals_serial_decisions():
    _, s1, _, sched1, _ = setup_multi()
    _, s2, _, sched2, _ = setup_multi()
    pipelined = sched1.match_cycle_pipelined()
    serial = {p.name: sched2.match_cycle(p) for p in s2.pools.values()}
    for name in pipelined:
        a = {(j.uuid, o.hostname) for j, o in pipelined[name].matched}
        b = {(j.uuid, o.hostname) for j, o in serial[name].matched}
        assert a == b


def test_transactions_commit_in_pool_order():
    _, store, _, scheduler, _ = setup_multi(n_pools=4)
    created_pools = []
    store.add_watcher(
        lambda e: created_pools.append(store.jobs[e.data["job"]].pool)
        if e.kind == "instance/created" else None)
    scheduler.match_cycle_pipelined()
    assert created_pools, "no launch transactions observed"
    # pool k's create transactions all land before pool k+1's first one
    assert created_pools == sorted(created_pools)


def test_overlap_accounting_fields():
    _, store, _, scheduler, _ = setup_multi()
    scheduler.match_cycle_pipelined()
    records = scheduler.recorder.records_json(limit=4)
    assert len(records) == 4
    for r in records:
        assert r["pipelined"] is True
        assert r["pipeline_wall_s"] > 0
        assert 0.0 <= r["overlap_fraction"] < 1.0
        assert "dispatch" in r["phases"] and "solve" in r["phases"]
        # every record of the pass shares the pass-level accounting
        assert r["pipeline_wall_s"] == records[0]["pipeline_wall_s"]
    # summed per-pool phase time can only exceed the wall by the overlap
    summed = sum(r["device_s"] + r["host_s"] for r in records)
    assert records[0]["overlap_s"] <= summed


def test_solve_failure_does_not_wedge_neighbor_pools(monkeypatch):
    _, store, _, scheduler, jobs = setup_multi(n_pools=3)
    # pin the fallback-DISABLED semantics: a solve failure skips the
    # pool's jobs for the cycle (the CPU-fallback reaction is covered in
    # tests/test_faults.py)
    scheduler.config.match.device_fallback_cycles = 0
    from cook_tpu.scheduler import pipeline as pipeline_mod

    real_dispatch = pipeline_mod.dispatch_pool_solve

    class Boom:
        def fetch(self):
            raise RuntimeError("injected device error")

    def dispatch(prepared, config, **kw):
        if prepared.pool.name == "pool1":
            return Boom()
        return real_dispatch(prepared, config, **kw)

    monkeypatch.setattr(pipeline_mod, "dispatch_pool_solve", dispatch)
    outcomes = scheduler.match_cycle_pipelined()
    # pools 0 and 2 matched normally
    for p in (0, 2):
        assert len(outcomes[f"pool{p}"].matched) == 5
    # pool1's jobs wait a cycle with the solve-failed reason
    assert outcomes["pool1"].matched == []
    assert len(outcomes["pool1"].unmatched) == 5
    for job in jobs:
        if job.pool == "pool1":
            assert store.jobs[job.uuid].state == JobState.WAITING
            cycle_id, code, _ = scheduler.recorder.job_reason(job.uuid)
            assert code == flight_codes.SOLVE_FAILED


def test_cpu_fallback_solve_raising_does_not_reenter_fallback(monkeypatch):
    """A pool ALREADY degraded to the CPU fallback whose reference solve
    raises at fetch has no further tier to degrade to: its jobs wait a
    cycle (solve-failed), the fallback budget is NOT reset, and the
    neighbor pools still match."""
    _, store, _, scheduler, jobs = setup_multi(n_pools=3)
    scheduler.config.match.device_fallback_cycles = 4
    from cook_tpu.scheduler import matcher as matcher_mod
    from cook_tpu.scheduler import pipeline as pipeline_mod
    from cook_tpu.scheduler.matcher import PoolMatchState

    scheduler.pool_match_state["pool1"] = PoolMatchState(
        num_considerable=scheduler.config.match.max_jobs_considered,
        fallback_cycles_left=2, fallback_reason="solve-error")
    calls = []
    real = matcher_mod.cpu_fallback_solve

    def cpu_solve(prepared, config):
        calls.append(prepared.pool.name)
        if prepared.pool.name == "pool1":
            raise RuntimeError("reference solver crashed")
        return real(prepared, config)

    monkeypatch.setattr(matcher_mod, "cpu_fallback_solve", cpu_solve)
    monkeypatch.setattr(pipeline_mod, "cpu_fallback_solve", cpu_solve)
    outcomes = scheduler.match_cycle_pipelined()
    for p in (0, 2):
        assert len(outcomes[f"pool{p}"].matched) == 5
    assert outcomes["pool1"].matched == []
    assert len(outcomes["pool1"].unmatched) == 5
    for job in jobs:
        if job.pool == "pool1":
            assert store.jobs[job.uuid].state == JobState.WAITING
            _, code, _ = scheduler.recorder.job_reason(job.uuid)
            assert code == flight_codes.SOLVE_FAILED
    # the failing CPU solve ran ONCE (no unprotected re-run) and did not
    # re-enter the fallback episode (enter_device_fallback would reset
    # the budget to 4)
    assert calls.count("pool1") == 1
    state = scheduler.pool_match_state["pool1"]
    assert state.fallback_cycles_left == 1
    assert state.fallback_reason == "solve-error"


def test_serial_cpu_fallback_solve_raising_degrades_to_solve_failed(
        monkeypatch):
    """The SERIAL path's analog of the guard above: a degraded pool whose
    reference solve raises must not let the exception escape match_cycle
    — its jobs wait with solve-failed, the fallback budget is not reset,
    and the other pools still match."""
    _, store, _, scheduler, jobs = setup_multi(n_pools=2)
    scheduler.config.match.device_fallback_cycles = 4
    from cook_tpu.scheduler import matcher as matcher_mod
    from cook_tpu.scheduler.matcher import PoolMatchState

    scheduler.pool_match_state["pool1"] = PoolMatchState(
        num_considerable=scheduler.config.match.max_jobs_considered,
        fallback_cycles_left=2, fallback_reason="solve-error")
    real = matcher_mod.cpu_fallback_solve

    def cpu_solve(prepared, config):
        if prepared.pool.name == "pool1":
            raise RuntimeError("reference solver crashed")
        return real(prepared, config)

    monkeypatch.setattr(matcher_mod, "cpu_fallback_solve", cpu_solve)
    outcomes = {p.name: scheduler.match_cycle(p)
                for p in store.pools.values()}
    assert len(outcomes["pool0"].matched) == 5
    assert outcomes["pool1"].matched == []
    assert len(outcomes["pool1"].unmatched) == 5
    for job in jobs:
        if job.pool == "pool1":
            assert store.jobs[job.uuid].state == JobState.WAITING
            _, code, _ = scheduler.recorder.job_reason(job.uuid)
            assert code == flight_codes.SOLVE_FAILED
    state = scheduler.pool_match_state["pool1"]
    assert state.fallback_cycles_left == 1
    assert state.fallback_reason == "solve-error"


# --------------------------------------------------------- launch fan-out


class FailingCluster(MockCluster):
    """launch_tasks raises mid fan-out (backend RPC failure)."""

    def launch_tasks(self, pool, specs):
        raise ConnectionError("backend unreachable")


def test_async_launch_failure_flows_to_store():
    _, store, _, scheduler, jobs = setup_multi(n_pools=2,
                                               cluster_cls=FailingCluster)
    scheduler.match_cycle_pipelined()
    assert scheduler.drain_launches(timeout=10)
    expected_code = REASONS_BY_NAME["launch-failed"].code
    for job in jobs:
        live = store.jobs[job.uuid]
        # launch-failed is mea-culpa: the instance failed, the job
        # re-queues without consuming its retry budget
        assert live.state == JobState.WAITING
        [inst] = store.job_instances(job.uuid)
        assert inst.status == InstanceStatus.FAILED
        assert inst.reason_code == expected_code
        _, code, _ = scheduler.recorder.job_reason(job.uuid)
        assert code == flight_codes.LAUNCH_FAILED


def test_serial_launch_failure_caught_per_cluster():
    """A raising cluster fails ITS specs with launch-failed and the other
    clusters' launches still happen (the historic behavior aborted the
    remaining clusters and left transacted tasks dangling)."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    bad = FailingCluster(
        "bad", [MockHost(node_id="b0", hostname="b0", mem=4000, cpus=8)],
        clock=clock)
    good = MockCluster(
        "good", [MockHost(node_id="g0", hostname="g0", mem=4000, cpus=8)],
        clock=clock)
    scheduler = Scheduler(store, [bad, good], SchedulerConfig())
    jobs = [make_job(user="a", mem=3000, cpus=6),   # fills one host
            make_job(user="b", mem=3000, cpus=6)]
    store.submit_jobs(jobs)
    outcome = scheduler.match_cycle(store.pools["default"])
    assert len(outcome.matched) == 2
    by_host = {inst.hostname: inst
               for job in jobs for inst in store.job_instances(job.uuid)}
    assert by_host["b0"].status == InstanceStatus.FAILED
    assert by_host["b0"].reason_code == REASONS_BY_NAME["launch-failed"].code
    assert by_host["g0"].status == InstanceStatus.RUNNING


class SlowCluster(MockCluster):
    """Instrumented backend: records whether a kill ever interleaved a
    mid-flight launch (the kill-lock must make that impossible)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.in_launch = False
        self.kill_during_launch = False

    def launch_tasks(self, pool, specs):
        self.in_launch = True
        time.sleep(0.3)
        super().launch_tasks(pool, specs)
        self.in_launch = False

    def kill_task(self, task_id):
        self.kill_during_launch |= self.in_launch
        super().kill_task(task_id)


def test_async_launch_completion_races_kill():
    clock = FakeClock()
    cluster = SlowCluster(
        "slow", [MockHost(node_id="h0", hostname="h0", mem=4000, cpus=8)],
        clock=clock)
    from cook_tpu.cluster.base import TaskSpec

    spec = TaskSpec(task_id="t-1", job_uuid="j-1", user="u", command="true",
                    mem=100, cpus=1, gpus=0, node_id="h0", hostname="h0")
    cluster.launch_tasks_async("default", [spec])
    # let the worker enter launch_tasks, then race a kill against it
    deadline = time.time() + 5
    while not cluster.in_launch and time.time() < deadline:
        time.sleep(0.005)
    assert cluster.in_launch
    t0 = time.perf_counter()
    cluster.safe_kill_task("t-1")
    waited = time.perf_counter() - t0
    assert cluster.wait_launches(timeout=5)
    assert not cluster.kill_during_launch
    # the kill blocked on the kill-lock until the launch finished
    assert waited > 0.05
    assert "t-1" not in cluster.running


def test_kill_racing_queued_launch_batch_is_not_resurrected():
    """The kill-lock only excludes kills during the backend call itself;
    a kill landing while the batch still sits in the async launch queue
    must not be undone when the batch finally runs."""
    clock = FakeClock()
    cluster = SlowCluster(
        "slow", [MockHost(node_id="h0", hostname="h0", mem=4000, cpus=8)],
        clock=clock)
    from cook_tpu.cluster.base import TaskSpec

    def spec(n):
        return TaskSpec(task_id=f"t-{n}", job_uuid=f"j-{n}", user="u",
                        command="true", mem=100, cpus=1, gpus=0,
                        node_id="h0", hostname="h0")

    cluster.launch_tasks_async("default", [spec(1)])   # occupies the worker
    cluster.launch_tasks_async("default", [spec(2)])   # sits in the queue
    deadline = time.time() + 5
    while not cluster.in_launch and time.time() < deadline:
        time.sleep(0.005)
    cluster.safe_kill_task("t-2")                      # races the queued batch
    assert cluster.wait_launches(timeout=5)
    assert "t-1" in cluster.running
    assert "t-2" not in cluster.running                # not resurrected


def test_launch_executor_completion_tracking():
    clock = FakeClock()
    cluster = SlowCluster(
        "slow", [MockHost(node_id="h0", hostname="h0", mem=4000, cpus=8)],
        clock=clock)
    from cook_tpu.cluster.base import TaskSpec

    spec = TaskSpec(task_id="t-2", job_uuid="j-2", user="u", command="true",
                    mem=100, cpus=1, gpus=0, node_id="h0", hostname="h0")
    cluster.launch_tasks_async("default", [spec])
    assert cluster.pending_launches() >= 1
    assert cluster.wait_launches(timeout=5)
    assert cluster.pending_launches() == 0
    assert "t-2" in cluster.running


# ---------------------------------------------------------- encode cache


def one_pool_store(n_hosts=3, n_jobs=4):
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    hosts = [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4000, cpus=8)
             for i in range(n_hosts)]
    cluster = MockCluster("mock", hosts, clock=clock)
    jobs = [make_job(user="a", mem=50_000, cpus=1) for _ in range(n_jobs)]
    store.submit_jobs(jobs)  # too big to match: stay considerable forever
    return clock, store, cluster, jobs


def prepare_once(store, cluster, cache):
    from cook_tpu.scheduler.matcher import (
        PoolMatchState,
        prepare_pool_problem,
    )
    from cook_tpu.scheduler.ranking import rank_pool

    pool = store.pools["default"]
    queue = rank_pool(store, pool)
    state = PoolMatchState(num_considerable=1000)
    return prepare_pool_problem(store, pool, queue, [cluster], MatchConfig(),
                                state, encode_cache=cache)


def test_encode_cache_rows_reused_and_correct():
    _, store, cluster, jobs = one_pool_store()
    cache = EncodeCache(store)
    p1 = prepare_once(store, cluster, cache)
    assert set(cache._pools["default"].rows) == {j.uuid for j in jobs}
    p2 = prepare_once(store, cluster, cache)
    np.testing.assert_array_equal(p1.feasible, p2.feasible)
    # cached rows match a cold (cache-less) encode exactly
    p3 = prepare_once(store, cluster, None)
    np.testing.assert_array_equal(p2.feasible, p3.feasible)


def test_encode_cache_invalidates_on_job_kill():
    _, store, cluster, jobs = one_pool_store()
    cache = EncodeCache(store)
    prepare_once(store, cluster, cache)
    victim = jobs[0]
    store.kill_jobs([victim.uuid])
    assert victim.uuid not in cache._pools["default"].rows


def test_encode_cache_invalidates_on_offer_rescind():
    _, store, cluster, _ = one_pool_store()
    cache = EncodeCache(store)
    p1 = prepare_once(store, cluster, cache)
    fp1 = cache._pools["default"].nodes_fp
    cluster.remove_host("h2")
    p2 = prepare_once(store, cluster, cache)
    assert cache._pools["default"].nodes_fp != fp1
    assert p2.feasible.shape[1] == p1.feasible.shape[1] - 1
    # rows re-encoded against the new node set
    parity = prepare_once(store, cluster, None)
    np.testing.assert_array_equal(p2.feasible, parity.feasible)


def test_encode_cache_vetoes_row_cached_during_invalidation():
    """An event dropping a job's rows WHILE its row is being recomputed
    (the compute read the store before the event) must veto that row's
    write-back — otherwise the stale row is served until the next
    event."""
    _, store, cluster, jobs = one_pool_store()
    cache = EncodeCache(store)
    from cook_tpu.scheduler.constraints import encode_nodes

    offers = [(cluster, o) for o in cluster.pending_offers("default")]
    nodes, fp = cache.encoded_nodes("default", offers)
    victim = jobs[0]

    def compute(subset, pre_rows):
        # the invalidating event lands mid-compute
        cache._on_event(type("E", (), {
            "kind": "instance/status",
            "data": {"job": victim.uuid}})())
        return np.ones((len(subset), nodes.n), dtype=bool)

    cache.feasibility("default", jobs, nodes.n, fp, compute)
    rows = cache._pools["default"].rows
    assert victim.uuid not in rows
    assert all(j.uuid in rows for j in jobs[1:])
    # the next cycle recomputes and re-caches the victim's row normally
    cache.feasibility("default", jobs, nodes.n, fp,
                      lambda subset, pre: np.ones((len(subset), nodes.n),
                                                  dtype=bool))
    assert victim.uuid in rows


def test_encode_cache_invalidates_on_quota_change():
    _, store, cluster, _ = one_pool_store()
    cache = EncodeCache(store)
    prepare_once(store, cluster, cache)
    epoch = cache.epoch
    # a generous quota still admits the jobs — the point is the EVENT
    # conservatively invalidates, not that the jobs stop being considered
    store.set_quota(Quota(user="a", pool="default",
                          resources=Resources(mem=1e9, cpus=1e9, gpus=1e9)))
    assert cache.epoch > epoch
    # stale-epoch rows are not served: the next prepare recomputes them
    entry = cache._pools["default"]
    stale = {uuid: tag for uuid, (tag, _) in entry.rows.items()}
    prepare_once(store, cluster, cache)
    for uuid, (tag, _) in entry.rows.items():
        assert tag == cache.epoch, f"row {uuid} kept stale epoch {stale}"


# ------------------------------------------------- batched pool-axis pad


def test_batched_mesh_pads_any_pool_count():
    """The sharded batched path engages for pool counts that don't divide
    the mesh size, and the padded batch keeps ONE XLA program across pool
    counts (CompileObservatory-inducing, same pattern as ops/elastic)."""
    from cook_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()  # 8 virtual cpu devices
    telemetry = None
    for n_pools in (3, 5, 8):
        clock = FakeClock()
        store = JobStore(clock=clock)
        hosts = []
        for p in range(n_pools):
            store.set_pool(Pool(name=f"pool{p}"))
            for i in range(3):
                hosts.append(MockHost(node_id=f"p{p}h{i}",
                                      hostname=f"p{p}h{i}",
                                      mem=4000, cpus=8, pool=f"pool{p}"))
        cluster = MockCluster("mock", hosts, clock=clock)
        scheduler = Scheduler(store, [cluster], SchedulerConfig())
        if telemetry is None:
            telemetry = scheduler.telemetry
        else:
            scheduler.telemetry = telemetry  # shared compile observatory
        jobs = []
        for p in range(n_pools):
            for i in range(4):
                jobs.append(make_job(user=f"u{i % 2}", pool=f"pool{p}",
                                     mem=500, cpus=1))
        store.submit_jobs(jobs)
        outcomes = scheduler.match_cycle_all_pools(mesh=mesh)
        assert sum(len(o.matched) for o in outcomes.values()) == len(jobs)
    stats = telemetry.observatory.stats()
    # 3, 5, and 8 pools all padded to one 8-pool program
    assert stats["match_batched"]["programs"] == 1
