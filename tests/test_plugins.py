"""Plugin seam tests (reference: plugins/{definitions,submission,launch}
+ pool plugin): submission validate/modify, launch filter with TTL cache,
completion handler, pool selection, plugin loading."""
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import JobState, Pool
from cook_tpu.models.store import JobStore
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread
from cook_tpu.scheduler.core import Scheduler
from cook_tpu.scheduler.plugins import (
    PluginRegistry,
    PluginResult,
    load_plugin,
)
from tests.conftest import FakeClock, make_job

import requests


class RejectBigJobs:
    def check_job_submission(self, spec, user, pool):
        if float(spec.get("mem", 0)) > 1000:
            return PluginResult(False, "too big for this cluster")
        return PluginResult(True)


class AddLabel:
    def modify_job(self, spec, user, pool):
        labels = dict(spec.get("labels", {}))
        labels["injected"] = "yes"
        return {**spec, "labels": labels}


class HoldUser:
    """Launch filter: holds a specific user's jobs back."""

    def __init__(self, user="held"):
        self.user = user
        self.calls = 0

    def check_job_launch(self, job):
        self.calls += 1
        if job.user == self.user:
            return PluginResult(False, "held")  # default TTL (60s)
        return PluginResult(True)


class RecordCompletions:
    def __init__(self):
        self.seen = []

    def on_instance_completion(self, job, instance):
        self.seen.append((job.uuid, instance.status.value))


def test_submission_plugins_via_api():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    plugins = PluginRegistry()
    plugins.submission_validators.append(RejectBigJobs())
    plugins.submission_modifiers.append(AddLabel())
    api = CookApi(store, None, ApiConfig(), plugins)
    srv = ServerThread(api).start()
    try:
        h = {"X-Cook-Requesting-User": "u"}
        r = requests.post(f"{srv.url}/jobs",
                          json={"jobs": [{"command": "x", "mem": 5000}]},
                          headers=h)
        assert r.status_code == 400
        assert "too big" in r.json()["error"]
        r = requests.post(f"{srv.url}/jobs",
                          json={"jobs": [{"command": "x", "mem": 100}]},
                          headers=h)
        assert r.status_code == 201
        uuid = r.json()["jobs"][0]
        job = requests.get(f"{srv.url}/jobs/{uuid}", headers=h).json()
        assert job["labels"]["injected"] == "yes"
    finally:
        srv.stop()


def test_launch_filter_holds_jobs_with_cache():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m", [MockHost(node_id="h0", hostname="h0", mem=4000, cpus=8)],
        clock=clock)
    plugins = PluginRegistry()
    holder = HoldUser()
    plugins.launch_filters.append(holder)
    scheduler = Scheduler(store, [cluster], plugins=plugins)
    held = make_job(user="held")
    free = make_job(user="free")
    store.submit_jobs([held, free])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    matched = {j.uuid for j, _ in outcome.matched}
    assert free.uuid in matched and held.uuid not in matched
    calls_before = holder.calls
    # second cycle within the TTL: cached, no new plugin call for held
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    assert holder.calls == calls_before
    # after TTL expiry the plugin is consulted again
    clock.advance(70_000)
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    assert holder.calls > calls_before


def test_completion_handler_fires():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m", [MockHost(node_id="h0", hostname="h0", mem=4000, cpus=8)],
        clock=clock)
    plugins = PluginRegistry()
    recorder = RecordCompletions()
    plugins.completion_handlers.append(recorder)
    scheduler = Scheduler(store, [cluster], plugins=plugins)
    job = make_job()
    store.submit_jobs([job])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    cluster.advance_to(10_000_000)
    assert (job.uuid, "success") in recorder.seen


def test_load_plugin_dotted_path():
    plugin = load_plugin("cook_tpu.scheduler.plugins:AttributePoolSelector")
    assert plugin.select_pool({"pool": "x"}, "default") == "x"
    assert plugin.select_pool({}, "default") == "default"
    # module-path form (pytest may import this test module under a
    # different name, so compare by class name, not identity)
    fn = load_plugin("tests.test_plugins.RecordCompletions")
    assert type(fn).__name__ == "RecordCompletions"


def test_pool_mover_adjuster_deterministic_rollout():
    """plugins/pool_mover.clj semantics: a configured portion of a user's
    jobs moves to the destination pool by stable uuid-hash bucket — the
    same job always lands on the same side."""
    from cook_tpu.scheduler.plugins import PoolMoverAdjuster

    mover = PoolMoverAdjuster({
        "default": {"destination_pool": "beta",
                    "users": {"alice": {"portion": 0.5}}},
    })
    jobs = [make_job(user="alice").with_(uuid=f"job-{i}")
            for i in range(200)]
    moved = sum(mover.adjust_job(j).pool == "beta" for j in jobs)
    assert 60 < moved < 140  # ~50% by hash bucket
    # deterministic: re-adjusting gives identical outcomes
    assert [mover.adjust_job(j).pool for j in jobs] == \
        [mover.adjust_job(j).pool for j in jobs]
    # other users and other pools never move
    assert mover.adjust_job(make_job(user="bob")).pool == "default"
    assert mover.adjust_job(
        make_job(user="alice", pool="gamma")).pool == "gamma"
    # portion 1.0 moves everything, 0.0 nothing
    all_in = PoolMoverAdjuster({"default": {
        "destination_pool": "beta", "users": {"alice": {"portion": 1.0}}}})
    assert all(all_in.adjust_job(j).pool == "beta" for j in jobs)


def test_pool_mover_through_rest_submission():
    """The adjuster seam is wired into POST /jobs: adjusted jobs land in
    the destination pool; an adjuster pointing at a missing pool keeps
    the submission pool (catch-and-keep)."""
    from cook_tpu.scheduler.plugins import (
        PoolMoverAdjuster,
        registry_from_config,
    )

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    store.set_pool(Pool(name="beta"))
    plugins = registry_from_config({
        "pool_mover": {"default": {"destination_pool": "beta",
                                   "users": {"u": {"portion": 1.0}}}},
    })
    assert isinstance(plugins.job_adjusters[0], PoolMoverAdjuster)
    api = CookApi(store, None, ApiConfig(), plugins)
    srv = ServerThread(api).start()
    try:
        h = {"X-Cook-Requesting-User": "u"}
        r = requests.post(f"{srv.url}/jobs",
                          json={"jobs": [{"command": "x", "mem": 100}]},
                          headers=h)
        assert r.status_code == 201
        uuid = r.json()["jobs"][0]
        assert store.jobs[uuid].pool == "beta"
        # destination pool vanishes: jobs stay where they were submitted
        del store.pools["beta"]
        r = requests.post(f"{srv.url}/jobs",
                          json={"jobs": [{"command": "x", "mem": 100}]},
                          headers=h)
        assert r.status_code == 201
        assert store.jobs[r.json()["jobs"][0]].pool == "default"
    finally:
        srv.stop()


def test_registry_from_config_dotted_paths():
    from cook_tpu.scheduler.plugins import registry_from_config

    registry = registry_from_config({
        "submission_validators": ["tests.test_plugins:RejectBigJobs"],
        "pool_selector": "cook_tpu.scheduler.plugins:AttributePoolSelector",
    })
    assert type(registry.submission_validators[0]).__name__ == "RejectBigJobs"
    assert registry.validate_submission({"mem": 5000}, "u", "p").accepted \
        is False
