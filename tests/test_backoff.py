"""Head-of-queue fairness backoff (scheduler.clj:1613-1651): an unmatched
queue head shrinks the considerable window; a matched head resets it."""
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.matcher import MatchConfig
from tests.conftest import FakeClock, make_job


def test_backoff_shrinks_and_resets():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m", [MockHost(node_id="h0", hostname="h0", mem=1000, cpus=8)],
        clock=clock)
    scheduler = Scheduler(
        store, [cluster],
        SchedulerConfig(match=MatchConfig(max_jobs_considered=100,
                                          scaleback=0.5)),
    )
    pool = store.pools["default"]
    # head job can never match (too big for the host but autoscaling off →
    # via a job that fits size caps but not current free resources)
    blocker = make_job(user="a", mem=900, cpus=8, priority=99)
    fillers = [make_job(user="b", mem=100, cpus=1) for _ in range(3)]
    store.submit_jobs([blocker] + fillers)
    # occupy most of the host so the blocker can't fit
    occupant = make_job(user="c", mem=500, cpus=1, priority=100)
    store.submit_jobs([occupant])
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)  # occupant (priority 100) matches first
    assert store.jobs[occupant.uuid].state.value == "running"

    state = scheduler.pool_match_state["default"]
    assert state.num_considerable == 100  # head matched -> reset
    # now blocker is head and cannot fit (500 used, 900 needed)
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    assert state.num_considerable == 50   # shrunk by scaleback
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    assert state.num_considerable == 25
    # the fillers still matched even while the head is stuck
    assert all(store.jobs[f.uuid].state.value == "running" for f in fillers)
    # complete the occupant; the head matches and the window resets
    cluster.advance_to(10_000_000)
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    assert store.jobs[blocker.uuid].state.value == "running"
    assert state.num_considerable == 100
