"""Multi-process shard-group runtime (cook_tpu/mp/).

Covers the ISSUE-16 tentpole invariants without subprocesses: the
deterministic shard-group topology and route map, GroupShardRouter's
misrouted-key contract (REST 421, never a wrong-segment write), the
worker's single-group REST surface, cross-group 2PC (ascending order,
all-or-nothing veto, journaled decision, idempotent replay), the
shard-aware front end (header passthrough, idempotent resubmit,
scatter-merge), and supervisor failover via check_once() + standby
adoption.  Everything runs in-process; the subprocess spawn path is
exercised by the killed-worker chaos drill (tools/chaos.py).
"""
import asyncio
import json
import os

import pytest
import requests

from cook_tpu.models.entities import Pool
from cook_tpu.mp import (GroupShardRouter, ShardGroupTopology,
                         build_route_map, read_route_map, write_route_map)
from cook_tpu.mp.twopc import DecisionLog, TwoPCCoordinator
from cook_tpu.mp.worker import ShardGroupWorker
from cook_tpu.shard.router import MisroutedKey, ShardRouter

HDRS = {"X-Cook-Requesting-User": "alice"}


def job_spec(uuid, pool, command="true"):
    return {"uuid": uuid, "command": command, "pool": pool,
            "mem": 64, "cpus": 1}


# -------------------------------------------------------------- topology


@pytest.mark.parametrize("n_shards,n_groups",
                         [(8, 4), (7, 3), (4, 4), (5, 1)])
def test_topology_blocks_partition_the_shard_space(n_shards, n_groups):
    topo = ShardGroupTopology(n_shards, n_groups)
    covered = []
    for g in range(n_groups):
        block = topo.shards_of_group(g)
        assert block == tuple(sorted(block))  # contiguous, ascending
        assert block == tuple(range(block[0], block[-1] + 1))
        covered.extend(block)
        for shard in block:
            assert topo.group_of_shard(shard) == g
    assert covered == list(range(n_shards))  # exact partition


def test_topology_key_routing_matches_global_hash():
    topo = ShardGroupTopology(8, 3)
    router = ShardRouter(8)
    for pool in ("prod", "dev", "gpu-a"):
        assert topo.group_for_pool(pool) == \
            topo.group_of_shard(router.shard_for_pool(pool))
    for user in ("alice", "bob"):
        assert topo.group_for_user(user) == \
            topo.group_of_shard(router.shard_for_user(user))


def test_topology_distinct_pool_helper():
    topo = ShardGroupTopology(4, 4)
    pools = topo.pools_for_distinct_groups()
    assert sorted(topo.group_for_pool(p) for p in pools) == [0, 1, 2, 3]


def test_topology_validation():
    with pytest.raises(ValueError):
        ShardGroupTopology(4, 5)  # more groups than shards
    with pytest.raises(ValueError):
        ShardGroupTopology(4, 0)
    with pytest.raises(ValueError):
        ShardGroupTopology(4, 2).shards_of_group(2)


def test_route_map_roundtrip(tmp_path):
    path = str(tmp_path / "mp" / "routemap.json")
    assert read_route_map(path) is None  # missing: not an error
    topo = ShardGroupTopology(4, 2)
    route_map = build_route_map(topo, {
        0: {"url": "http://w0", "rpc_url": "http://w0r", "alive": True},
    }, map_seq=7)
    write_route_map(path, route_map)
    loaded = read_route_map(path)
    assert loaded == route_map
    assert loaded["map_seq"] == 7
    by_group = {e["group"]: e for e in loaded["groups"]}
    assert by_group[0]["alive"] and by_group[0]["shards"] == [0, 1]
    assert not by_group[1]["alive"]  # no entry -> dead, still serialized
    write_route_map(path, {"schema": "bogus/v9"})
    with pytest.raises(ValueError):
        read_route_map(path)


def test_group_router_localizes_owned_and_raises_on_misroute():
    global_router = ShardRouter(4)
    owned = (2, 3)
    router = GroupShardRouter(4, owned)
    assert router.n_shards == 2  # LOCAL count: sizes the ShardedStore
    for pool in (f"p{i}" for i in range(16)):
        g = global_router.shard_for_pool(pool)
        if g in owned:
            assert router.shard_for_pool(pool) == owned.index(g)
        else:
            with pytest.raises(MisroutedKey) as exc:
                router.shard_for_pool(pool)
            assert exc.value.owner_shard == g
    with pytest.raises(ValueError):
        GroupShardRouter(4, ())


# ---------------------------------------------------- worker REST surface


@pytest.fixture
def worker0(tmp_path):
    """Group 0 of a 2-shard/2-group fleet, REST + RPC up in-process."""
    topo = ShardGroupTopology(2, 2)
    pools = topo.pools_for_distinct_groups()
    worker = ShardGroupWorker(
        data_dir=str(tmp_path), n_shards=2, group=0,
        shards=topo.shards_of_group(0),
        pools=("default", *pools)).start()
    yield worker, pools
    worker.stop()


def test_worker_serves_only_owned_shards(worker0):
    worker, pools = worker0
    owned_pool, other_pool = pools  # one per group, by construction
    resp = requests.post(f"{worker.url}/jobs", headers=HDRS,
                         json={"jobs": [job_spec("j-own", owned_pool)]})
    assert resp.status_code == 201
    assert requests.get(f"{worker.url}/jobs/j-own",
                        headers=HDRS).status_code == 200
    # the other group's pool was filtered at registration: a misdirected
    # submit is an error (unknown pool), never a wrong-segment write
    assert other_pool not in worker.store.pools
    resp = requests.post(f"{worker.url}/jobs", headers=HDRS,
                         json={"jobs": [job_spec("j-far", other_pool)]})
    assert resp.status_code == 400


def test_worker_answers_421_for_misrouted_keys(worker0):
    worker, pools = worker0
    # simulate the stale state the registration filter prevents: a pool
    # present in this worker's tables whose shard it does not own
    worker.store.shards[0].pools[pools[1]] = Pool(name=pools[1])
    for resp in (
        requests.get(f"{worker.url}/list", headers=HDRS,
                     params={"user": "alice"}),
        requests.post(f"{worker.url}/jobs", headers=HDRS,
                      json={"jobs": [job_spec("j-mis", pools[1])]}),
    ):
        assert resp.status_code == 421
        assert resp.headers["X-Cook-Owner-Shard"] == "1"
    assert "j-mis" not in worker.store.jobs


# ------------------------------------------------------- cross-group 2PC


class _Fleet:
    """Two in-process workers + a coordinator whose transport calls the
    participants directly (no sockets): the veto/replay state machine
    under test, not aiohttp."""

    def __init__(self, tmp_path, fail_commits_to=()):
        self.topo = ShardGroupTopology(2, 2)
        self.pools = self.topo.pools_for_distinct_groups()
        self.workers = {
            g: ShardGroupWorker(
                data_dir=str(tmp_path), n_shards=2, group=g,
                shards=self.topo.shards_of_group(g),
                pools=("default", *self.pools))
            for g in (0, 1)}
        self.rpc_urls = {g: f"fleet://{g}" for g in (0, 1)}
        self.fail_commits_to = set(fail_commits_to)
        self.log_path = str(tmp_path / "2pc-decisions.jsonl")

    async def post(self, url, body, timeout_s):
        base, _, method = url.partition("/rpc/2pc/")
        group = int(base.rsplit("/", 1)[-1])
        if method == "commit" and group in self.fail_commits_to:
            raise ConnectionError("injected commit outage")
        participant = self.workers[group].participant
        if method == "abort":
            return 200, participant.abort(body["txn_id"])
        return 200, getattr(participant, method)(
            body["txn_id"], body["op"], body["user"],
            body.get("payload") or {})

    def coordinator(self, **kw):
        kw.setdefault("retry_backoff_s", 0.0)
        return TwoPCCoordinator(self.post, DecisionLog(self.log_path),
                                **kw)

    def submit_payloads(self, suffix=""):
        return {g: {"jobs": [job_spec(f"j{g}{suffix}", self.pools[g])]}
                for g in (0, 1)}

    def stop(self):
        for worker in self.workers.values():
            worker.stop()


@pytest.fixture
def fleet(tmp_path):
    fleet = _Fleet(tmp_path)
    yield fleet
    fleet.stop()


def test_twopc_commits_on_every_group(fleet):
    coord = fleet.coordinator()
    result = asyncio.run(coord.run(
        txn_id="t-ok", op="jobs/submit", user="alice",
        per_group=fleet.submit_payloads(), rpc_urls=fleet.rpc_urls))
    assert result["ok"] and result["pending_groups"] == []
    for g in (0, 1):
        assert f"j{g}" in fleet.workers[g].store.jobs
    # done marker written: nothing left to replay
    assert coord.decisions.outstanding() == {}
    # a replayed commit is answered from the idempotency table
    reply = fleet.workers[0].participant.commit(
        "t-ok", "jobs/submit", "alice", fleet.submit_payloads()[0])
    assert reply["ok"] and reply["duplicate"]


def test_twopc_veto_aborts_all_groups(fleet):
    coord = fleet.coordinator()
    per_group = fleet.submit_payloads()
    per_group[1]["jobs"][0]["command"] = ""  # group 1 must veto
    result = asyncio.run(coord.run(
        txn_id="t-veto", op="jobs/submit", user="alice",
        per_group=per_group, rpc_urls=fleet.rpc_urls))
    assert not result["ok"]
    assert result["status"] == 400 and result["vetoed_by"] == 1
    # all-or-nothing: group 0 prepared fine but must not apply, and no
    # decision was journaled (presumed abort)
    for g in (0, 1):
        assert f"j{g}" not in fleet.workers[g].store.jobs
        assert fleet.workers[g].participant._pending == {}
    assert coord.decisions.outstanding() == {}
    assert os.path.getsize(fleet.log_path) == 0


def test_twopc_decision_survives_commit_outage_and_replays(tmp_path):
    fleet = _Fleet(tmp_path, fail_commits_to={1})
    try:
        coord = fleet.coordinator(commit_attempts=2)
        result = asyncio.run(coord.run(
            txn_id="t-replay", op="jobs/submit", user="alice",
            per_group=fleet.submit_payloads(), rpc_urls=fleet.rpc_urls))
        # the decision stands: group 0 applied, group 1 is pending
        assert result["ok"] and result["pending_groups"] == [1]
        assert "j0" in fleet.workers[0].store.jobs
        assert "j1" not in fleet.workers[1].store.jobs
        # a NEW coordinator on the same decision log (front-end restart)
        # finishes the transaction once the participant is reachable —
        # group 1 lost its staged prepare?  No: it re-validates from the
        # payload the decision carries either way.
        fleet.fail_commits_to.clear()
        fresh = fleet.coordinator()
        report = asyncio.run(fresh.replay())
        assert report == {"outstanding": 1, "finished": 1,
                          "still_pending": 0}
        assert "j1" in fleet.workers[1].store.jobs
        # replay converges: running it again finds nothing outstanding
        assert asyncio.run(fresh.replay())["outstanding"] == 0
    finally:
        fleet.stop()


def test_decision_log_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "decisions.jsonl")
    log = DecisionLog(path)
    log.append({"txn_id": "a", "decision": "commit", "groups": {},
                "op": "jobs/submit"})
    log.append({"txn_id": "b", "decision": "commit", "groups": {},
                "op": "jobs/submit"})
    log.append({"txn_id": "a", "decision": "done"})
    log.close()
    with open(path, "a") as f:
        f.write('{"txn_id": "c", "decision": "com')  # torn: not durable
    outstanding = DecisionLog(path).outstanding()
    assert set(outstanding) == {"b"}  # a is done, c presumed abort


# ----------------------------------- front end + supervisor (in-process)


@pytest.fixture(scope="module")
def runtime():
    from cook_tpu.mp.supervisor import MpRuntime

    runtime = MpRuntime(n_groups=2, standbys=0, inprocess=True,
                        poll_s=30.0)  # tests drive check_once directly
    yield runtime
    runtime.stop()


def test_frontend_forwards_with_headers_and_idempotency(runtime):
    pool = runtime.pools[1]  # one group's pool: a single-group forward
    body = {"jobs": [job_spec("fe-j0", pool)]}
    headers = {**HDRS, "X-Cook-Txn-Id": "fe-txn-1"}
    first = requests.post(f"{runtime.url}/jobs", json=body,
                          headers=headers)
    assert first.status_code == 201
    assert first.headers["X-Cook-Shard-Group"].isdigit()
    # same txn-id again: the worker's idempotency table answers through
    # the front end because the forward preserves body + headers
    second = requests.post(f"{runtime.url}/jobs", json=body,
                           headers=headers)
    assert second.status_code == 201 and second.json() == first.json()
    # per-uuid read routes to the owning group
    read = requests.get(f"{runtime.url}/jobs/fe-j0", headers=HDRS)
    assert read.status_code == 200
    assert read.headers["X-Cook-Shard-Group"] == \
        first.headers["X-Cook-Shard-Group"]


def test_frontend_cross_group_submit_and_kill_via_2pc(runtime):
    pool_a, pool_b = runtime.pools[1], runtime.pools[2]
    resp = requests.post(f"{runtime.url}/jobs", headers=HDRS, json={
        "jobs": [job_spec("", pool_a) | {"uuid": ""},
                 job_spec("", pool_b) | {"uuid": ""}]})
    assert resp.status_code == 201
    assert "," in resp.headers["X-Cook-Shard-Group"]  # 2PC, two groups
    assert resp.headers["X-Cook-Txn-Id"]
    uuids = resp.json()["jobs"]
    assert len(uuids) == 2
    groups = set()
    for uuid in uuids:
        read = requests.get(f"{runtime.url}/jobs/{uuid}", headers=HDRS)
        assert read.status_code == 200
        groups.add(read.headers["X-Cook-Shard-Group"])
    assert len(groups) == 2  # the jobs really live on different workers
    kill = requests.delete(f"{runtime.url}/jobs", headers=HDRS,
                           params=[("uuid", u) for u in uuids])
    assert kill.status_code == 204
    for uuid in uuids:
        job = requests.get(f"{runtime.url}/jobs/{uuid}",
                           headers=HDRS).json()
        assert job["status"] in ("failed", "completed")


def test_frontend_scatter_merges_fleet_wide_reads(runtime):
    # /pools is scatter-merged: the union of every group's owned pools
    names = {p["name"] for p in
             requests.get(f"{runtime.url}/pools", headers=HDRS).json()}
    assert set(runtime.pools) <= names
    # /list merges both groups' jobs for one user
    for g, pool in enumerate(runtime.pools[1:]):
        requests.post(f"{runtime.url}/jobs", headers=HDRS,
                      json={"jobs": [job_spec(f"sc-{g}", pool)]})
    listed = {j["uuid"] for j in requests.get(
        f"{runtime.url}/list", headers=HDRS,
        params={"user": "alice"}).json()}
    assert {"sc-0", "sc-1"} <= listed


def test_frontend_debug_surfaces(runtime):
    shards = requests.get(f"{runtime.url}/debug/shards",
                          headers=HDRS).json()
    assert shards["n_groups"] == 2
    assert all(e["alive"] for e in shards["groups"])
    assert "breakers" in shards
    frontend = requests.get(f"{runtime.url}/debug/frontend",
                            headers=HDRS).json()
    assert "twopc" in frontend


def test_supervisor_failover_promotes_standby_and_keeps_acks(tmp_path):
    from cook_tpu.mp.supervisor import MpRuntime

    runtime = MpRuntime(n_groups=2, standbys=1, inprocess=True,
                        poll_s=30.0, data_dir=str(tmp_path))
    try:
        pool0, pool1 = runtime.pools[1], runtime.pools[2]
        acked = []
        for i, pool in enumerate((pool0, pool1)):
            resp = requests.post(
                f"{runtime.url}/jobs", headers=HDRS,
                json={"jobs": [job_spec(f"fo-{i}", pool)]})
            assert resp.status_code == 201
            acked.append(f"fo-{i}")
        victim = runtime.supervisor.topology.group_for_pool(pool0)
        old_url = runtime.supervisor.workers[victim].describe["url"]
        runtime.supervisor.kill_worker(victim)
        assert runtime.supervisor.check_once() == [victim]
        # the map now points the victim group at the adopted standby
        route_map = read_route_map(runtime.supervisor.map_path)
        assert route_map["map_seq"] >= 3
        entry = {e["group"]: e for e in route_map["groups"]}[victim]
        assert entry["alive"] and entry["url"] != old_url
        # the front end re-reads the map on mtime; poll until it did
        deadline = 50
        while deadline:
            shards = requests.get(f"{runtime.url}/debug/shards",
                                  headers=HDRS).json()
            if shards["map_seq"] == route_map["map_seq"]:
                break
            deadline -= 1
            import time
            time.sleep(0.1)
        assert deadline, "front end never picked up the new map"
        # nothing acked was lost: the standby recovered the journal
        # segments, and fresh writes land on the adopter
        for uuid in acked:
            assert requests.get(f"{runtime.url}/jobs/{uuid}",
                                headers=HDRS).status_code == 200
        resp = requests.post(f"{runtime.url}/jobs", headers=HDRS,
                             json={"jobs": [job_spec("fo-new", pool0)]})
        assert resp.status_code == 201
    finally:
        runtime.stop()
