"""Multi-process shard-group runtime (cook_tpu/mp/).

Covers the ISSUE-16 tentpole invariants without subprocesses: the
deterministic shard-group topology and route map, GroupShardRouter's
misrouted-key contract (REST 421, never a wrong-segment write), the
worker's single-group REST surface, cross-group 2PC (ascending order,
all-or-nothing veto, journaled decision, idempotent replay), the
shard-aware front end (header passthrough, idempotent resubmit,
scatter-merge), and supervisor failover via check_once() + standby
adoption.  Everything runs in-process; the subprocess spawn path is
exercised by the killed-worker chaos drill (tools/chaos.py).
"""
import asyncio
import json
import os

import pytest
import requests

from cook_tpu.models.entities import Pool
from cook_tpu.mp import (GroupShardRouter, ShardGroupTopology,
                         build_route_map, read_route_map, write_route_map)
from cook_tpu.mp.twopc import DecisionLog, TwoPCCoordinator
from cook_tpu.mp.worker import ShardGroupWorker
from cook_tpu.obs import distributed
from cook_tpu.shard.router import MisroutedKey, ShardRouter
from cook_tpu.utils import tracing

HDRS = {"X-Cook-Requesting-User": "alice"}


def job_spec(uuid, pool, command="true"):
    return {"uuid": uuid, "command": command, "pool": pool,
            "mem": 64, "cpus": 1}


# -------------------------------------------------------------- topology


@pytest.mark.parametrize("n_shards,n_groups",
                         [(8, 4), (7, 3), (4, 4), (5, 1)])
def test_topology_blocks_partition_the_shard_space(n_shards, n_groups):
    topo = ShardGroupTopology(n_shards, n_groups)
    covered = []
    for g in range(n_groups):
        block = topo.shards_of_group(g)
        assert block == tuple(sorted(block))  # contiguous, ascending
        assert block == tuple(range(block[0], block[-1] + 1))
        covered.extend(block)
        for shard in block:
            assert topo.group_of_shard(shard) == g
    assert covered == list(range(n_shards))  # exact partition


def test_topology_key_routing_matches_global_hash():
    topo = ShardGroupTopology(8, 3)
    router = ShardRouter(8)
    for pool in ("prod", "dev", "gpu-a"):
        assert topo.group_for_pool(pool) == \
            topo.group_of_shard(router.shard_for_pool(pool))
    for user in ("alice", "bob"):
        assert topo.group_for_user(user) == \
            topo.group_of_shard(router.shard_for_user(user))


def test_topology_distinct_pool_helper():
    topo = ShardGroupTopology(4, 4)
    pools = topo.pools_for_distinct_groups()
    assert sorted(topo.group_for_pool(p) for p in pools) == [0, 1, 2, 3]


def test_topology_validation():
    with pytest.raises(ValueError):
        ShardGroupTopology(4, 5)  # more groups than shards
    with pytest.raises(ValueError):
        ShardGroupTopology(4, 0)
    with pytest.raises(ValueError):
        ShardGroupTopology(4, 2).shards_of_group(2)


def test_route_map_roundtrip(tmp_path):
    path = str(tmp_path / "mp" / "routemap.json")
    assert read_route_map(path) is None  # missing: not an error
    topo = ShardGroupTopology(4, 2)
    route_map = build_route_map(topo, {
        0: {"url": "http://w0", "rpc_url": "http://w0r", "alive": True},
    }, map_seq=7)
    write_route_map(path, route_map)
    loaded = read_route_map(path)
    assert loaded == route_map
    assert loaded["map_seq"] == 7
    by_group = {e["group"]: e for e in loaded["groups"]}
    assert by_group[0]["alive"] and by_group[0]["shards"] == [0, 1]
    assert not by_group[1]["alive"]  # no entry -> dead, still serialized
    write_route_map(path, {"schema": "bogus/v9"})
    with pytest.raises(ValueError):
        read_route_map(path)


def test_group_router_localizes_owned_and_raises_on_misroute():
    global_router = ShardRouter(4)
    owned = (2, 3)
    router = GroupShardRouter(4, owned)
    assert router.n_shards == 2  # LOCAL count: sizes the ShardedStore
    for pool in (f"p{i}" for i in range(16)):
        g = global_router.shard_for_pool(pool)
        if g in owned:
            assert router.shard_for_pool(pool) == owned.index(g)
        else:
            with pytest.raises(MisroutedKey) as exc:
                router.shard_for_pool(pool)
            assert exc.value.owner_shard == g
    with pytest.raises(ValueError):
        GroupShardRouter(4, ())


# ---------------------------------------------------- worker REST surface


@pytest.fixture
def worker0(tmp_path):
    """Group 0 of a 2-shard/2-group fleet, REST + RPC up in-process."""
    topo = ShardGroupTopology(2, 2)
    pools = topo.pools_for_distinct_groups()
    worker = ShardGroupWorker(
        data_dir=str(tmp_path), n_shards=2, group=0,
        shards=topo.shards_of_group(0),
        pools=("default", *pools)).start()
    yield worker, pools
    worker.stop()


def test_worker_serves_only_owned_shards(worker0):
    worker, pools = worker0
    owned_pool, other_pool = pools  # one per group, by construction
    resp = requests.post(f"{worker.url}/jobs", headers=HDRS,
                         json={"jobs": [job_spec("j-own", owned_pool)]})
    assert resp.status_code == 201
    assert requests.get(f"{worker.url}/jobs/j-own",
                        headers=HDRS).status_code == 200
    # the other group's pool was filtered at registration: a misdirected
    # submit is an error (unknown pool), never a wrong-segment write
    assert other_pool not in worker.store.pools
    resp = requests.post(f"{worker.url}/jobs", headers=HDRS,
                         json={"jobs": [job_spec("j-far", other_pool)]})
    assert resp.status_code == 400


def test_worker_answers_421_for_misrouted_keys(worker0):
    worker, pools = worker0
    # simulate the stale state the registration filter prevents: a pool
    # present in this worker's tables whose shard it does not own
    worker.store.shards[0].pools[pools[1]] = Pool(name=pools[1])
    for resp in (
        requests.get(f"{worker.url}/list", headers=HDRS,
                     params={"user": "alice"}),
        requests.post(f"{worker.url}/jobs", headers=HDRS,
                      json={"jobs": [job_spec("j-mis", pools[1])]}),
    ):
        assert resp.status_code == 421
        assert resp.headers["X-Cook-Owner-Shard"] == "1"
    assert "j-mis" not in worker.store.jobs


# ------------------------------------------------------- cross-group 2PC


class _Fleet:
    """Two in-process workers + a coordinator whose transport calls the
    participants directly (no sockets): the veto/replay state machine
    under test, not aiohttp."""

    def __init__(self, tmp_path, fail_commits_to=()):
        self.topo = ShardGroupTopology(2, 2)
        self.pools = self.topo.pools_for_distinct_groups()
        self.workers = {
            g: ShardGroupWorker(
                data_dir=str(tmp_path), n_shards=2, group=g,
                shards=self.topo.shards_of_group(g),
                pools=("default", *self.pools))
            for g in (0, 1)}
        self.rpc_urls = {g: f"fleet://{g}" for g in (0, 1)}
        self.fail_commits_to = set(fail_commits_to)
        self.log_path = str(tmp_path / "2pc-decisions.jsonl")

    async def post(self, url, body, timeout_s, headers=None):
        base, _, method = url.partition("/rpc/2pc/")
        group = int(base.rsplit("/", 1)[-1])
        if method == "commit" and group in self.fail_commits_to:
            raise ConnectionError("injected commit outage")
        participant = self.workers[group].participant
        # the coordinator's trace context rides the headers, exactly as
        # _RpcSurface would hand it to the participant
        parent = (headers or {}).get(distributed.PARENT_SPAN_HEADER)
        if method == "abort":
            return 200, participant.abort(body["txn_id"], parent=parent)
        return 200, getattr(participant, method)(
            body["txn_id"], body["op"], body["user"],
            body.get("payload") or {}, parent=parent)

    def coordinator(self, **kw):
        kw.setdefault("retry_backoff_s", 0.0)
        return TwoPCCoordinator(self.post, DecisionLog(self.log_path),
                                **kw)

    def submit_payloads(self, suffix=""):
        return {g: {"jobs": [job_spec(f"j{g}{suffix}", self.pools[g])]}
                for g in (0, 1)}

    def stop(self):
        for worker in self.workers.values():
            worker.stop()


@pytest.fixture
def fleet(tmp_path):
    fleet = _Fleet(tmp_path)
    yield fleet
    fleet.stop()


def test_twopc_commits_on_every_group(fleet):
    coord = fleet.coordinator()
    result = asyncio.run(coord.run(
        txn_id="t-ok", op="jobs/submit", user="alice",
        per_group=fleet.submit_payloads(), rpc_urls=fleet.rpc_urls))
    assert result["ok"] and result["pending_groups"] == []
    for g in (0, 1):
        assert f"j{g}" in fleet.workers[g].store.jobs
    # done marker written: nothing left to replay
    assert coord.decisions.outstanding() == {}
    # a replayed commit is answered from the idempotency table
    reply = fleet.workers[0].participant.commit(
        "t-ok", "jobs/submit", "alice", fleet.submit_payloads()[0])
    assert reply["ok"] and reply["duplicate"]


def test_twopc_veto_aborts_all_groups(fleet):
    coord = fleet.coordinator()
    per_group = fleet.submit_payloads()
    per_group[1]["jobs"][0]["command"] = ""  # group 1 must veto
    result = asyncio.run(coord.run(
        txn_id="t-veto", op="jobs/submit", user="alice",
        per_group=per_group, rpc_urls=fleet.rpc_urls))
    assert not result["ok"]
    assert result["status"] == 400 and result["vetoed_by"] == 1
    # all-or-nothing: group 0 prepared fine but must not apply, and no
    # decision was journaled (presumed abort)
    for g in (0, 1):
        assert f"j{g}" not in fleet.workers[g].store.jobs
        assert fleet.workers[g].participant._pending == {}
    assert coord.decisions.outstanding() == {}
    assert os.path.getsize(fleet.log_path) == 0


def test_twopc_decision_survives_commit_outage_and_replays(tmp_path):
    fleet = _Fleet(tmp_path, fail_commits_to={1})
    try:
        coord = fleet.coordinator(commit_attempts=2)
        result = asyncio.run(coord.run(
            txn_id="t-replay", op="jobs/submit", user="alice",
            per_group=fleet.submit_payloads(), rpc_urls=fleet.rpc_urls))
        # the decision stands: group 0 applied, group 1 is pending
        assert result["ok"] and result["pending_groups"] == [1]
        assert "j0" in fleet.workers[0].store.jobs
        assert "j1" not in fleet.workers[1].store.jobs
        # a NEW coordinator on the same decision log (front-end restart)
        # finishes the transaction once the participant is reachable —
        # group 1 lost its staged prepare?  No: it re-validates from the
        # payload the decision carries either way.
        fleet.fail_commits_to.clear()
        fresh = fleet.coordinator()
        report = asyncio.run(fresh.replay())
        assert report == {"outstanding": 1, "finished": 1,
                          "still_pending": 0}
        assert "j1" in fleet.workers[1].store.jobs
        # replay converges: running it again finds nothing outstanding
        assert asyncio.run(fresh.replay())["outstanding"] == 0
    finally:
        fleet.stop()


def test_twopc_veto_trace_names_vetoing_group(fleet):
    """A vetoed cross-group txn leaves a stitched trace naming WHO
    said no: the coordinator's failed prepare span carries the group,
    and the participant lands a twopc.veto marker on its own track."""
    coord = fleet.coordinator()
    per_group = fleet.submit_payloads("-vt")
    per_group[1]["jobs"][0]["command"] = ""  # group 1 must veto
    result = asyncio.run(coord.run(
        txn_id="t-veto-trace", op="jobs/submit", user="alice",
        per_group=per_group, rpc_urls=fleet.rpc_urls))
    assert not result["ok"] and result["vetoed_by"] == 1
    spans = tracing.spans_for_txn("t-veto-trace")
    by_name = {}
    for entry in spans:
        by_name.setdefault(entry["name"], []).append(entry)
    assert any(e["tags"].get("process") == "worker-g1"
               for e in by_name["twopc.veto"])
    assert any(e["tags"].get("group") == 1 and e["tags"].get("error")
               for e in by_name["twopc.prepare"])
    # participants opened their phase spans under the coordinator's
    # X-Cook-Parent-Span, from BOTH groups' tracks
    prepares = by_name["mp.participant.prepare"]
    assert {e["parent"] for e in prepares} == {"twopc.prepare"}
    assert {"worker-g0", "worker-g1"} <= {
        e["tags"].get("process") for e in prepares}
    # group 0 prepared fine and was unwound: its abort is in the trace
    assert any(e["tags"].get("process") == "worker-g0"
               for e in by_name["mp.participant.abort"])
    # presumed abort: no decision write ever happened
    assert "twopc.decision_write" not in by_name


def test_twopc_replay_trace_names_replayed_group(tmp_path):
    """A torn decision (commit outage after the fsynced decision write)
    replays to convergence, and the stitched trace names the group the
    replay finished: the failed + successful commit RPCs and the
    participant's apply all carry the same txn id."""
    fleet = _Fleet(tmp_path, fail_commits_to={1})
    try:
        coord = fleet.coordinator(commit_attempts=1)
        result = asyncio.run(coord.run(
            txn_id="t-replay-trace", op="jobs/submit", user="alice",
            per_group=fleet.submit_payloads("-rt"),
            rpc_urls=fleet.rpc_urls))
        assert result["ok"] and result["pending_groups"] == [1]
        fleet.fail_commits_to.clear()
        asyncio.run(fleet.coordinator().replay())
        spans = tracing.spans_for_txn("t-replay-trace")
        commits = [e for e in spans if e["name"] == "twopc.commit"]
        assert any(e["tags"].get("group") == 1 and e["tags"].get("error")
                   for e in commits), "the outage never hit the ring"
        assert any(e["tags"].get("group") == 1
                   and not e["tags"].get("error")
                   for e in commits), "no successful replayed commit"
        applied = [e for e in spans
                   if e["name"] == "mp.participant.commit"
                   and e["tags"].get("process") == "worker-g1"]
        assert applied and applied[-1]["parent"] == "twopc.commit"
        # exactly one fsynced decision write, on the coordinator lane
        decisions = [e for e in spans
                     if e["name"] == "twopc.decision_write"]
        assert len(decisions) == 1
        assert decisions[0]["tags"]["process"] == "coordinator"
    finally:
        fleet.stop()


def test_decision_log_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "decisions.jsonl")
    log = DecisionLog(path)
    log.append({"txn_id": "a", "decision": "commit", "groups": {},
                "op": "jobs/submit"})
    log.append({"txn_id": "b", "decision": "commit", "groups": {},
                "op": "jobs/submit"})
    log.append({"txn_id": "a", "decision": "done"})
    log.close()
    with open(path, "a") as f:
        f.write('{"txn_id": "c", "decision": "com')  # torn: not durable
    outstanding = DecisionLog(path).outstanding()
    assert set(outstanding) == {"b"}  # a is done, c presumed abort


# ----------------------------------- front end + supervisor (in-process)


@pytest.fixture(scope="module")
def runtime():
    from cook_tpu.mp.supervisor import MpRuntime

    runtime = MpRuntime(n_groups=2, standbys=0, inprocess=True,
                        poll_s=30.0)  # tests drive check_once directly
    yield runtime
    runtime.stop()


def test_frontend_forwards_with_headers_and_idempotency(runtime):
    pool = runtime.pools[1]  # one group's pool: a single-group forward
    body = {"jobs": [job_spec("fe-j0", pool)]}
    headers = {**HDRS, "X-Cook-Txn-Id": "fe-txn-1"}
    first = requests.post(f"{runtime.url}/jobs", json=body,
                          headers=headers)
    assert first.status_code == 201
    assert first.headers["X-Cook-Shard-Group"].isdigit()
    # same txn-id again: the worker's idempotency table answers through
    # the front end because the forward preserves body + headers
    second = requests.post(f"{runtime.url}/jobs", json=body,
                           headers=headers)
    assert second.status_code == 201 and second.json() == first.json()
    # per-uuid read routes to the owning group
    read = requests.get(f"{runtime.url}/jobs/fe-j0", headers=HDRS)
    assert read.status_code == 200
    assert read.headers["X-Cook-Shard-Group"] == \
        first.headers["X-Cook-Shard-Group"]


def test_frontend_cross_group_submit_and_kill_via_2pc(runtime):
    pool_a, pool_b = runtime.pools[1], runtime.pools[2]
    resp = requests.post(f"{runtime.url}/jobs", headers=HDRS, json={
        "jobs": [job_spec("", pool_a) | {"uuid": ""},
                 job_spec("", pool_b) | {"uuid": ""}]})
    assert resp.status_code == 201
    assert "," in resp.headers["X-Cook-Shard-Group"]  # 2PC, two groups
    assert resp.headers["X-Cook-Txn-Id"]
    uuids = resp.json()["jobs"]
    assert len(uuids) == 2
    groups = set()
    for uuid in uuids:
        read = requests.get(f"{runtime.url}/jobs/{uuid}", headers=HDRS)
        assert read.status_code == 200
        groups.add(read.headers["X-Cook-Shard-Group"])
    assert len(groups) == 2  # the jobs really live on different workers
    kill = requests.delete(f"{runtime.url}/jobs", headers=HDRS,
                           params=[("uuid", u) for u in uuids])
    assert kill.status_code == 204
    for uuid in uuids:
        job = requests.get(f"{runtime.url}/jobs/{uuid}",
                           headers=HDRS).json()
        assert job["status"] in ("failed", "completed")


def test_frontend_scatter_merges_fleet_wide_reads(runtime):
    # /pools is scatter-merged: the union of every group's owned pools
    names = {p["name"] for p in
             requests.get(f"{runtime.url}/pools", headers=HDRS).json()}
    assert set(runtime.pools) <= names
    # /list merges both groups' jobs for one user
    for g, pool in enumerate(runtime.pools[1:]):
        requests.post(f"{runtime.url}/jobs", headers=HDRS,
                      json={"jobs": [job_spec(f"sc-{g}", pool)]})
    listed = {j["uuid"] for j in requests.get(
        f"{runtime.url}/list", headers=HDRS,
        params={"user": "alice"}).json()}
    assert {"sc-0", "sc-1"} <= listed


def test_frontend_debug_surfaces(runtime):
    shards = requests.get(f"{runtime.url}/debug/shards",
                          headers=HDRS).json()
    assert shards["n_groups"] == 2
    assert all(e["alive"] for e in shards["groups"])
    assert "breakers" in shards
    frontend = requests.get(f"{runtime.url}/debug/frontend",
                            headers=HDRS).json()
    assert "twopc" in frontend


def test_frontend_merged_trace_for_cross_group_submit(runtime):
    """The ISSUE's acceptance artifact: ONE merged Chrome trace for a
    cross-group submit, with front-end (pid 0), coordinator-decision
    (pid 1), and both participants' (pid >= 2) tracks under one
    txn id."""
    pool_a, pool_b = runtime.pools[1], runtime.pools[2]
    txn_id = "txn-merged-trace"
    resp = requests.post(
        f"{runtime.url}/jobs",
        headers={**HDRS, "X-Cook-Txn-Id": txn_id},
        json={"jobs": [job_spec("tr-a", pool_a),
                       job_spec("tr-b", pool_b)]})
    assert resp.status_code == 201
    raw = requests.get(f"{runtime.url}/debug/trace", headers=HDRS,
                       params={"txn_id": txn_id, "format": "raw"}).json()
    assert raw["txn_id"] == txn_id and raw["groups_failed"] == []
    procs = {e["process"] for e in raw["spans"]}
    assert "frontend" in procs and "coordinator" in procs
    assert len({p for p in procs if p.startswith("worker-g")}) >= 2
    names = {e["name"] for e in raw["spans"]}
    assert {"mp.submit_2pc", "twopc.prepare", "twopc.decision_write",
            "twopc.commit", "mp.participant.prepare",
            "mp.participant.commit"} <= names
    # chrome rendering: one pid track per process, contract pids
    chrome = requests.get(f"{runtime.url}/debug/trace", headers=HDRS,
                          params={"txn_id": txn_id}).json()
    events = chrome["traceEvents"]
    pids = {e["args"]["name"]: e["pid"] for e in events
            if e["name"] == "process_name"}
    assert pids["frontend"] == 0 and pids["coordinator"] == 1
    worker_pids = [p for label, p in pids.items()
                   if label.startswith("worker-g")]
    assert len(worker_pids) >= 2 and all(p >= 2 for p in worker_pids)
    decision = [e for e in events if e["name"] == "twopc.decision_write"]
    assert decision and decision[0]["pid"] == 1  # the commit point
    # bad requests fail crisply
    assert requests.get(f"{runtime.url}/debug/trace",
                        headers=HDRS).status_code == 400
    assert requests.get(f"{runtime.url}/debug/trace", headers=HDRS,
                        params={"txn_id": "x", "format": "svg"}
                        ).status_code == 400


def test_frontend_reports_nonzero_hop_splits(runtime):
    """/debug/frontend splits forward time by hop from the worker's
    X-Cook-Hop-Walls response header + the front end's own stamps."""
    pool = runtime.pools[1]
    for i in range(3):
        resp = requests.post(f"{runtime.url}/jobs", headers=HDRS,
                             json={"jobs": [job_spec(f"hop-{i}", pool)]})
        assert resp.status_code == 201
        assert "server" in resp.headers.get("X-Cook-Hop-Walls", ""), \
            "worker phase walls never propagated back out"
    g = str(runtime.supervisor.topology.group_for_pool(pool))
    frontend = requests.get(f"{runtime.url}/debug/frontend",
                            headers=HDRS).json()
    hops = frontend["per_group"][g]["hops"]
    for hop in ("queue", "transport", "apply", "fsync"):
        assert hops[hop]["count"] > 0, f"no {hop} samples"
        assert hops[hop]["p99_ms"] > 0.0, f"{hop} split is zero"


def test_frontend_timeline_stitches_twopc_decision(runtime):
    """/jobs/{uuid}/timeline through the front end folds the 2PC commit
    decision + done markers into a cross-group job's event stream."""
    pool_a, pool_b = runtime.pools[1], runtime.pools[2]
    resp = requests.post(f"{runtime.url}/jobs", headers=HDRS, json={
        "jobs": [job_spec("tl-a", pool_a), job_spec("tl-b", pool_b)]})
    assert resp.status_code == 201
    timeline = requests.get(f"{runtime.url}/jobs/tl-a/timeline",
                            headers=HDRS).json()
    kinds = [e["kind"] for e in timeline["events"]]
    assert "2pc-commit-decision" in kinds and "2pc-done" in kinds
    decision = next(e for e in timeline["events"]
                    if e["kind"] == "2pc-commit-decision")
    assert len(decision["groups"]) == 2
    assert set(decision["prepare_ms"]) == \
        {str(g) for g in decision["groups"]}
    twopc = timeline["twopc"]
    assert twopc["txn_id"] == decision["txn_id"]
    assert twopc["done_t"] >= twopc["decided_t"]
    # shared clock domain: the worker stamps jobs with wall-clock ms
    # (ShardGroupWorker's default clock), so the decision-log event
    # lands within seconds of the submit stamp — not decades away
    # (the decision write precedes the commit apply that stamps the
    # job, so the delta may be slightly negative)
    assert abs(decision["t_ms"] - timeline["submit_time_ms"]) < 60_000
    # a single-group job's timeline passes through unstitched
    requests.post(f"{runtime.url}/jobs", headers=HDRS,
                  json={"jobs": [job_spec("tl-solo", pool_a)]})
    solo = requests.get(f"{runtime.url}/jobs/tl-solo/timeline",
                        headers=HDRS).json()
    assert "twopc" not in solo
    # unknown uuid: 404, same contract as the worker's own surface
    assert requests.get(f"{runtime.url}/jobs/no-such/timeline",
                        headers=HDRS).status_code == 404


def test_cli_renders_twopc_timeline_and_trace_waterfall(
        runtime, tmp_path, capsys):
    """`cs timeline` names the 2PC hop and `cs trace` renders the
    merged cross-process waterfall when pointed at the mp front end."""
    from cook_tpu.client.cli import main as cli_main

    cfg = tmp_path / "cs.json"
    cfg.write_text(json.dumps(
        {"clusters": [{"name": "mp", "url": runtime.url}]}))
    txn_id = "cli-mp-trace"
    resp = requests.post(
        f"{runtime.url}/jobs",
        headers={**HDRS, "X-Cook-Txn-Id": txn_id},
        json={"jobs": [job_spec("cli-a", runtime.pools[1]),
                       job_spec("cli-b", runtime.pools[2])]})
    assert resp.status_code == 201
    assert cli_main(["--config", str(cfg), "--user", "alice",
                     "timeline", "cli-a"]) == 0
    out = capsys.readouterr().out
    assert "2PC commit decision across groups" in out
    assert "2PC done across groups" in out
    assert cli_main(["--config", str(cfg), "--user", "alice",
                     "trace", txn_id]) == 0
    out = capsys.readouterr().out
    for process in ("frontend", "coordinator", "worker-g"):
        assert process in out, f"{process} track missing from waterfall"
    assert "mp.submit_2pc" in out and "twopc.decision_write" in out
    assert "█" in out  # bars, not just labels
    # --json round-trips the merged raw body
    assert cli_main(["--config", str(cfg), "--user", "alice",
                     "trace", txn_id, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["spans"] and parsed["cluster"] == "mp"
    # an unknown txn id exits non-zero with a retention hint
    assert cli_main(["--config", str(cfg), "--user", "alice",
                     "trace", "never-seen"]) == 1
    assert "no spans" in capsys.readouterr().err


def test_supervisor_failover_promotes_standby_and_keeps_acks(tmp_path):
    from cook_tpu.mp.supervisor import MpRuntime

    runtime = MpRuntime(n_groups=2, standbys=1, inprocess=True,
                        poll_s=30.0, data_dir=str(tmp_path))
    try:
        pool0, pool1 = runtime.pools[1], runtime.pools[2]
        acked = []
        for i, pool in enumerate((pool0, pool1)):
            resp = requests.post(
                f"{runtime.url}/jobs", headers=HDRS,
                json={"jobs": [job_spec(f"fo-{i}", pool)]})
            assert resp.status_code == 201
            acked.append(f"fo-{i}")
        victim = runtime.supervisor.topology.group_for_pool(pool0)
        old_url = runtime.supervisor.workers[victim].describe["url"]
        runtime.supervisor.kill_worker(victim)
        assert runtime.supervisor.check_once() == [victim]
        # the map now points the victim group at the adopted standby
        route_map = read_route_map(runtime.supervisor.map_path)
        assert route_map["map_seq"] >= 3
        entry = {e["group"]: e for e in route_map["groups"]}[victim]
        assert entry["alive"] and entry["url"] != old_url
        # the front end re-reads the map on mtime; poll until it did
        deadline = 50
        while deadline:
            shards = requests.get(f"{runtime.url}/debug/shards",
                                  headers=HDRS).json()
            if shards["map_seq"] == route_map["map_seq"]:
                break
            deadline -= 1
            import time
            time.sleep(0.1)
        assert deadline, "front end never picked up the new map"
        # nothing acked was lost: the standby recovered the journal
        # segments, and fresh writes land on the adopter
        for uuid in acked:
            assert requests.get(f"{runtime.url}/jobs/{uuid}",
                                headers=HDRS).status_code == 200
        resp = requests.post(f"{runtime.url}/jobs", headers=HDRS,
                             json={"jobs": [job_spec("fo-new", pool0)]})
        assert resp.status_code == 201
        # federated incident: the fleet poller saw the victim's
        # ok->degraded edge and captured through the FRONT END's
        # recorder, embedding the mp evidence collectors
        fed = [b for b in runtime.frontend.incidents.bundles()
               if b["trigger"] == "fleet-peer"]
        assert fed, "no federated incident for the killed worker"
        bundle = runtime.frontend.incidents.get(fed[-1]["id"])
        assert bundle["verdict"]["federated"]
        assert bundle["verdict"]["peer"].rstrip("/") == \
            old_url.rstrip("/")
        assert "records" in bundle["decision_log"]
        assert set(bundle["breakers"]) == {"0", "1"}
        assert bundle["route_map"]["groups"]
        # ...and the front end's /debug/incidents serves the same index
        served = requests.get(f"{runtime.url}/debug/incidents",
                              headers=HDRS).json()
        assert fed[-1]["id"] in {b["id"] for b in served["incidents"]}
        # the adoption is traceable: the supervisor stamped the adopt
        # RPC with a failover correlation id, and the adopter opened
        # mp.adopt on its OWN group's track under mp.failover
        adopts = [e for e in tracing.recent_spans(4096)
                  if e["name"] == "mp.adopt"
                  and e["tags"].get("group") == victim]
        assert adopts, "no mp.adopt span for the failover"
        adopt = adopts[-1]
        assert adopt["parent"] == "mp.failover"
        assert adopt["tags"]["process"] == f"worker-g{victim}"
        failover_txn = adopt["tags"]["txn_id"]
        assert failover_txn.startswith(f"failover-{victim}-")
        stitched = tracing.spans_for_txn(failover_txn)
        assert {"mp.adopt", "mp.failover"} <= \
            {e["name"] for e in stitched}
    finally:
        runtime.stop()
