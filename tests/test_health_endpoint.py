"""GET /debug/health degradation-reason transitions, plus the device-
truth fields the obs/ layer adds to /debug/cycles, /unscheduled_jobs and
/metrics — the observability acceptance surface."""
import pytest
import requests

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.obs import DeviceTelemetry
from cook_tpu.ops.common import bucket_size
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from tests.conftest import FakeClock, make_job


@pytest.fixture(scope="module")
def server():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock",
        [MockHost(node_id=f"n{i}", hostname=f"n{i}", mem=4096, cpus=16)
         for i in range(4)],
        clock=clock,
    )
    config = SchedulerConfig()
    config.quality_sample_every = 1  # shadow-solve every cycle in tests
    scheduler = Scheduler(store, [cluster], config)
    api = CookApi(store, scheduler, ApiConfig(admins=("admin",)))
    srv = ServerThread(api).start()
    srv.clock = clock
    srv.store = store
    srv.scheduler = scheduler
    yield srv
    srv.stop()


def hdr(user="alice"):
    return {"X-Cook-Requesting-User": user}


@pytest.fixture
def fresh_telemetry(server):
    """Each test judges its own telemetry state: swap in a fresh facade
    (no device-memory probe — deterministic off-device)."""
    old = server.scheduler.telemetry
    # storm_warmup=0: the transition tests induce storms directly; the
    # first-boot warmup grace is covered at the unit level (test_obs)
    telemetry = DeviceTelemetry(memory_stats_fn=lambda: None,
                                storm_warmup=0)
    server.scheduler.telemetry = telemetry
    yield telemetry
    server.scheduler.telemetry = old


def get_health(server):
    r = requests.get(f"{server.url}/debug/health", headers=hdr())
    assert r.status_code == 200
    return r.json()


def test_healthy_by_default(server, fresh_telemetry):
    health = get_health(server)
    assert health["healthy"] and health["status"] == "ok"
    assert health["degradations"] == []
    # the check evidence is present even when green — device-telemetry
    # checks plus the merged control-plane contention checks
    assert set(health["checks"]) == {"compile", "quality", "solve_latency",
                                     "device_fallback", "device_memory",
                                     "contention", "fairness"}
    assert set(health["checks"]["contention"]) == {
        "store_lock", "journal", "replication", "commit_ack", "starvation"}


def test_recompile_storm_transition(server, fresh_telemetry):
    """Cycling padded shapes across N solves must flip the verdict to
    recompile-storm, and recover after a warm window."""
    for queue_len in [100, 1100, 2100, 4100, 8200, 100]:
        fresh_telemetry.record_solve(
            "match", (bucket_size(queue_len), 2048), "xla", 0.01)
    health = get_health(server)
    assert not health["healthy"]
    assert health["reasons"] == ["recompile-storm"]
    degradation = health["degradations"][0]
    assert degradation["op"] == "match"
    assert "padded-shape churn" in degradation["detail"]
    # warm same-shape solves drain the window -> healthy again
    for _ in range(40):
        fresh_telemetry.record_solve("match", (128, 2048), "xla", 0.01)
    assert get_health(server)["healthy"]


def test_quality_drift_transition(server, fresh_telemetry):
    quality = fresh_telemetry.quality
    for _ in range(12):
        quality.record_sample("default", 1.0)
    assert get_health(server)["healthy"]
    for _ in range(4):
        quality.record_sample("default", 0.90)
    health = get_health(server)
    assert "quality-drift" in health["reasons"]
    [degradation] = health["degradations"]
    assert degradation["pool"] == "default"
    assert degradation["efficiency"] == pytest.approx(0.90)
    for _ in range(8):
        quality.record_sample("default", 1.0)
    assert get_health(server)["healthy"]


def test_solve_latency_regression_transition(server, fresh_telemetry):
    fresh_telemetry.record_match_solve("default", (1024, 128), "xla", 5.0)
    for _ in range(16):
        fresh_telemetry.record_match_solve("default", (1024, 128), "xla",
                                           0.010)
    assert get_health(server)["healthy"]
    for _ in range(8):
        fresh_telemetry.record_match_solve("default", (1024, 128), "xla",
                                           0.120)
    health = get_health(server)
    assert health["reasons"] == ["solve-latency-regression"]
    [degradation] = health["degradations"]
    assert degradation["pool"] == "default"
    assert degradation["recent"] > degradation["baseline"]


def test_device_oom_risk_transition(server, fresh_telemetry):
    usage = {"fill": 0.5}

    def stats():
        return {"bytes_in_use": usage["fill"] * 100.0,
                "bytes_limit": 100.0, "peak_bytes_in_use": 95.0,
                "utilization": usage["fill"]}

    fresh_telemetry.health_monitor.memory_stats_fn = stats
    assert get_health(server)["healthy"]
    usage["fill"] = 0.97
    health = get_health(server)
    assert health["reasons"] == ["device-oom-risk"]
    assert "device memory 97%" in health["degradations"][0]["detail"]
    usage["fill"] = 0.4
    assert get_health(server)["healthy"]


# ----------------------------------------------- device truth on the wire


def run_cycle(server, n_jobs=2):
    uuids = []
    for _ in range(n_jobs):
        job = make_job(mem=64, cpus=0.5)
        server.store.submit_jobs([job])
        uuids.append(job.uuid)
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    return uuids


def test_cycle_records_carry_solve_identity(server):
    run_cycle(server)
    r = requests.get(f"{server.url}/debug/cycles?limit=1", headers=hdr())
    [record] = r.json()["cycles"]
    # default config: chunk=0 exact kernel over 64x64 padded buckets
    assert record["solve_shape"] == "64x64"
    assert record["backend"] == "exact"
    assert isinstance(record["compiled"], bool)


def test_compile_counts_reach_metrics_endpoint(server):
    """Acceptance: per-(op, shape, backend) compile counts at /metrics
    after real match cycles."""
    import re

    run_cycle(server)
    text = requests.get(f"{server.url}/metrics", headers=hdr()).text
    # the counter is process-global across test suites' schedulers, so
    # assert the labeled series exists with a positive count
    match = re.search(
        r'cook_obs_compile_count\{backend="exact",op="match",'
        r'shape="64x64"\} ([0-9.]+)', text)
    assert match is not None, "per-(op,shape,backend) series missing"
    assert float(match.group(1)) >= 1.0
    # the rank solve's padded task bucket is counted too
    assert 'op="rank"' in text
    assert "cook_obs_solve_seconds_bucket" in text


def test_unscheduled_jobs_reports_pool_solve(server):
    # an unsatisfiable job stays waiting with a reason code AND the
    # pool's current padded shape/backend for compile correlation
    job = make_job(mem=999999, cpus=64)
    server.store.submit_jobs([job])
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    r = requests.get(f"{server.url}/unscheduled_jobs",
                     params={"job": job.uuid}, headers=hdr())
    [entry] = r.json()
    solve = entry["pool_solve"]
    assert solve["backend"] == "exact"
    assert solve["op"] in ("match", "match_batched")
    assert "x" in solve["shape"]
    assert isinstance(solve["compiled"], bool)
    assert entry["reasons"]


def test_quality_monitor_sampled_real_cycles(server):
    """quality_sample_every=1: every solvable cycle shadow-solves; the
    exact kernel must match the CPU reference bit-for-bit (eff 1.0)."""
    telemetry = server.scheduler.telemetry
    run_cycle(server)
    stats = telemetry.quality.stats()["default"]
    assert stats["samples"] >= 1
    assert stats["last"] == pytest.approx(1.0)
    assert get_health(server)["checks"]["quality"]["default"]["last"] == \
        pytest.approx(1.0)
