"""Optimizer seam: cycle validation + the backlog purchase planner."""
import pytest

from cook_tpu.scheduler.optimizer import (
    BacklogPurchaseOptimizer,
    HostInfo,
    NoOpHostFeed,
    NoOpOptimizer,
    OptimizerCycle,
)
from tests.conftest import make_job


def test_noop_cycle_shape():
    cycle = OptimizerCycle()
    out = cycle.run([], [], {})
    assert out == {0: {"suggested-matches": {}, "suggested-purchases": {}}}
    assert cycle.latest_schedule == out


def test_malformed_schedule_rejected():
    class Bad(NoOpOptimizer):
        def produce_schedule(self, *a):
            return {"not-an-int": {}}

    cycle = OptimizerCycle(optimizer=Bad())
    with pytest.raises(ValueError):
        cycle.run([], [], {})


def test_backlog_purchase_sizing():
    class Feed(NoOpHostFeed):
        def get_available_host_info(self):
            return [
                HostInfo("small", count=100, cpus=8, mem=16000),
                HostInfo("gpu-box", count=10, cpus=32, mem=64000, gpus=8),
            ]

    queue = [make_job(mem=16000, cpus=8) for _ in range(5)]
    queue += [make_job(mem=1000, cpus=1, gpus=4)]
    cycle = OptimizerCycle(host_feed=Feed(),
                           optimizer=BacklogPurchaseOptimizer())
    out = cycle.run(queue, [], {"mem": 16000.0, "cpus": 8.0})
    purchases = out[0]["suggested-purchases"]
    # mem gap = 5*16000 + 1000 - 16000 spare = 65000 -> ceil = 5 smalls,
    # plus a gpu box for the gpu job
    assert purchases["small"] == 5
    assert purchases["gpu-box"] == 1


def test_no_purchases_when_capacity_covers():
    class Feed(NoOpHostFeed):
        def get_available_host_info(self):
            return [HostInfo("small", count=10, cpus=8, mem=16000)]

    queue = [make_job(mem=100, cpus=1)]
    cycle = OptimizerCycle(host_feed=Feed(),
                           optimizer=BacklogPurchaseOptimizer())
    out = cycle.run(queue, [], {"mem": 99999.0, "cpus": 999.0})
    assert out[0]["suggested-purchases"] == {}
