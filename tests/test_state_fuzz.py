"""Randomized torture test of the store + state machine: thousands of
random operations must never violate the core invariants (the role of the
reference's schema property tests)."""
import numpy as np

from cook_tpu.models.entities import InstanceStatus, JobState, Pool
from cook_tpu.models.reasons import REASONS_BY_CODE
from cook_tpu.models.state import attempts_consumed
from cook_tpu.models.store import JobStore, TransactionVetoed
from tests.conftest import FakeClock, make_job


def check_invariants(store: JobStore):
    for job in store.jobs.values():
        insts = store.job_instances(job.uuid)
        live = [i for i in insts if not i.status.terminal]
        # at most one live instance per job
        assert len(live) <= 1, job.uuid
        if job.state == JobState.WAITING:
            assert not live
        if job.state == JobState.RUNNING:
            assert live
        if job.state == JobState.COMPLETED and any(
            i.status == InstanceStatus.SUCCESS for i in insts
        ):
            pass  # success is terminal regardless of attempts
        # a WAITING job's consumed attempts never exceed its budget
        # (== is reachable: retries may legally shrink to exactly the
        # consumed count on a waiting job, matching the reference's
        # update-retry-count semantics)
        if job.state == JobState.WAITING and insts:
            assert attempts_consumed(job, insts) <= job.max_retries
    # index consistency
    for pool, ids in store._pool_pending.items():
        for uuid in ids:
            assert store.jobs[uuid].state == JobState.WAITING
    for pool, ids in store._pool_running.items():
        for uuid in ids:
            assert store.jobs[uuid].state == JobState.RUNNING


def test_store_fuzz():
    rng = np.random.default_rng(1234)
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    job_ids: list[str] = []
    task_seq = [0]
    reasons = list(REASONS_BY_CODE)

    def random_live_task():
        live = [t for t, i in store.instances.items() if not i.status.terminal]
        return live[rng.integers(len(live))] if live else None

    states_seen: set = set()
    for step in range(4000):
        op = rng.integers(0, 100)
        try:
            if op < 20 or not job_ids:
                job = make_job(user=f"u{rng.integers(5)}",
                               max_retries=int(rng.integers(1, 4)))
                store.submit_jobs([job])
                job_ids.append(job.uuid)
            elif op < 45:
                uuid = job_ids[rng.integers(len(job_ids))]
                task_seq[0] += 1
                store.create_instance(uuid, f"ft{task_seq[0]}",
                                      hostname=f"h{rng.integers(8)}")
            elif op < 60:
                t = random_live_task()
                if t:
                    store.update_instance_state(t, InstanceStatus.RUNNING)
            elif op < 80:
                t = random_live_task()
                if t:
                    status = (InstanceStatus.SUCCESS
                              if rng.uniform() < 0.4 else InstanceStatus.FAILED)
                    store.update_instance_state(
                        t, status, int(reasons[rng.integers(len(reasons))])
                    )
            elif op < 90:
                uuid = job_ids[rng.integers(len(job_ids))]
                store.kill_jobs([uuid])
                # fan-out: fail any live instances (scheduler's job normally)
                for inst in store.live_instances_of_job(uuid):
                    store.update_instance_state(
                        inst.task_id, InstanceStatus.FAILED, 1001)
            else:
                uuid = job_ids[rng.integers(len(job_ids))]
                store.retry_job(uuid, int(rng.integers(1, 6)))
        except (TransactionVetoed, ValueError):
            pass  # rejected ops are fine; invariants must still hold
        if step % 200 == 0:
            check_invariants(store)
            states_seen.update(j.state for j in store.jobs.values())
    check_invariants(store)
    states_seen.update(j.state for j in store.jobs.values())
    # sanity: the fuzz actually exercised all op kinds.  Checked over the
    # whole run, not the final snapshot — whether any job happens to be
    # RUNNING at step 4000 exactly depends on the rng trajectory, which
    # shifts whenever the reason registry grows a code
    assert len(job_ids) > 100
    assert JobState.COMPLETED in states_seen
    assert JobState.RUNNING in states_seen
