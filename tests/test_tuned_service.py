"""Tuned-config promotion into the SERVICE (not just the bench): tuned
defaults merge/override/disable in read_config, the effective-config
surface, and the runtime match-quality audit guard."""
import json

import pytest

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import JobState, Pool
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler import matcher as matcher_mod
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.matcher import MatchConfig
from cook_tpu.utils.config import (
    default_match_config,
    read_config,
    tuned_match_defaults,
)
from cook_tpu.utils.metrics import global_registry
from tests.conftest import FakeClock, make_job


@pytest.fixture
def tuned_file(tmp_path, monkeypatch):
    p = tmp_path / "tuned.json"
    p.write_text(json.dumps({
        "backend": "bucketed", "chunk": 2048, "rounds": 4, "passes": 3,
        "kc": 64, "measured_p50_ms": 123.0, "measured_packing_eff": 1.0,
    }))
    monkeypatch.setenv("COOK_TUNED_MATCH", str(p))
    return p


class TestTunedDefaults:
    def test_tuned_defaults_applied_without_match_section(self, tuned_file):
        s = read_config(None)
        assert s.match.chunk == 2048
        assert s.match.backend == "bucketed"
        assert s.match.chunk_rounds == 4
        assert s.match.chunk_passes == 3
        assert s.match.chunk_kc == 64

    def test_explicit_match_keys_override_tuned(self, tuned_file, tmp_path):
        cfg = tmp_path / "c.json"
        cfg.write_text(json.dumps({"match": {"chunk": 0}}))
        s = read_config(str(cfg))
        # the operator pinned chunk; everything they did NOT set still
        # comes from the tuned file
        assert s.match.chunk == 0
        assert s.match.backend == "bucketed"

    def test_pool_schedulers_also_get_tuned_defaults(self, tuned_file,
                                                     tmp_path):
        cfg = tmp_path / "c.json"
        cfg.write_text(json.dumps({
            "pool_schedulers": [{"pool_regex": "gpu.*",
                                 "match": {"max_jobs_considered": 7}}],
        }))
        s = read_config(str(cfg))
        assert s.match_config_for_pool("gpu1").chunk == 2048
        assert s.match_config_for_pool("gpu1").max_jobs_considered == 7

    def test_env_none_disables(self, monkeypatch):
        monkeypatch.setenv("COOK_TUNED_MATCH", "none")
        assert tuned_match_defaults() == {}
        s = read_config(None)
        assert s.match.chunk == 0  # pure dataclass default

    def test_repo_root_file_found_by_default(self, monkeypatch):
        # the checked-in tuned_match.json (sweep-promoted) must reach the
        # default service config — the VERDICT r2 "perf trap" regression
        monkeypatch.delenv("COOK_TUNED_MATCH", raising=False)
        tuned = tuned_match_defaults()
        assert tuned.get("chunk", 0) > 0
        assert default_match_config().chunk == tuned["chunk"]

    def test_default_match_config_override_precedence(self, tuned_file):
        m = default_match_config(chunk=512)
        assert m.chunk == 512
        assert m.backend == "bucketed"  # still from tuned


def _chunked_scheduler(audit_every):
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    hosts = [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4000, cpus=8)
             for i in range(4)]
    cluster = MockCluster("mock", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=64, quality_audit_every=audit_every)))
    return clock, store, cluster, scheduler


class TestQualityAudit:
    def test_audit_gauges_parity_every_cycle(self):
        clock, store, cluster, scheduler = _chunked_scheduler(audit_every=1)
        gauge = global_registry.gauge("match.quality_audit")
        gauge.set(-1.0, labels={"pool": "default"})
        store.submit_jobs([make_job(user="u1", mem=500, cpus=1)
                           for _ in range(8)])
        pool = store.pools["default"]
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
        assert matcher_mod.last_audit_thread is not None
        matcher_mod.last_audit_thread.join(timeout=30)
        ratio = gauge.value(labels={"pool": "default"})
        # tiny uncontended problem: the chunked kernel must match the
        # exact kernel's packing exactly
        assert ratio == pytest.approx(1.0)
        for job in store.jobs.values():
            assert job.state == JobState.RUNNING

    def test_audit_disabled_at_zero(self):
        clock, store, cluster, scheduler = _chunked_scheduler(audit_every=0)
        gauge = global_registry.gauge("match.quality_audit")
        gauge.set(-2.0, labels={"pool": "default"})
        store.submit_jobs([make_job(user="u1", mem=500, cpus=1)])
        pool = store.pools["default"]
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
        assert gauge.value(labels={"pool": "default"}) == -2.0

    def test_audit_covers_batched_path(self):
        clock = FakeClock()
        store = JobStore(clock=clock)
        for p in range(2):
            store.set_pool(Pool(name=f"pool{p}"))
        hosts = [MockHost(node_id=f"p{p}h{i}", hostname=f"p{p}h{i}",
                          mem=4000, cpus=8, pool=f"pool{p}")
                 for p in range(2) for i in range(2)]
        cluster = MockCluster("mock", hosts, clock=clock)
        scheduler = Scheduler(store, [cluster], SchedulerConfig(
            match=MatchConfig(chunk=64, quality_audit_every=1)))
        gauge = global_registry.gauge("match.quality_audit")
        for p in range(2):
            gauge.set(-3.0, labels={"pool": f"pool{p}"})
        store.submit_jobs([make_job(user="u1", pool=f"pool{p}",
                                    mem=500, cpus=1)
                           for p in range(2) for _ in range(4)])
        scheduler.match_cycle_all_pools()
        # single-flight: at least one pool's audit ran this cycle
        assert matcher_mod.last_audit_thread is not None
        matcher_mod.last_audit_thread.join(timeout=30)
        ratios = [gauge.value(labels={"pool": f"pool{p}"})
                  for p in range(2)]
        assert any(r == pytest.approx(1.0) for r in ratios)
