"""Leader election, config, and full-process wiring tests (reference:
components.clj startup + mesos.clj leadership + test_master_slave.py)."""
import json
import threading
import time

import pytest
import requests

from cook_tpu.components import build_process, shutdown, start_leader_duties
from cook_tpu.control.leader import (
    FileLeaseElector,
    InMemoryElector,
    LeaderSelector,
)
from cook_tpu.utils.config import read_config


class TestElectors:
    def test_in_memory_single_leader(self):
        a = InMemoryElector("g1", "a")
        b = InMemoryElector("g1", "b")
        assert a.try_acquire()
        assert not b.try_acquire()
        assert a.heartbeat()
        assert not b.heartbeat()
        a.release()
        assert b.try_acquire()
        assert b.current_leader() == "b"

    def test_file_lease_takeover_on_staleness(self, tmp_path):
        now = [0.0]
        clock = lambda: now[0]
        path = str(tmp_path / "lease")
        a = FileLeaseElector(path, "a", ttl_s=10, clock=clock)
        b = FileLeaseElector(path, "b", ttl_s=10, clock=clock)
        assert a.try_acquire()
        assert not b.try_acquire()
        now[0] += 5
        assert a.heartbeat()
        assert not b.try_acquire()
        now[0] += 11  # lease goes stale (leader died)
        assert b.try_acquire()
        assert not a.heartbeat()  # old leader lost
        assert b.current_leader() == "b"

    def test_selector_fail_fast_on_loss(self):
        elector = InMemoryElector("g2", "x")
        lost = threading.Event()
        sel = LeaderSelector(elector, poll_s=0.01, on_loss=lost.set)
        sel.wait_for_leadership()
        assert sel.is_leader
        t = sel.start_heartbeat_thread()
        # usurp leadership out from under it
        InMemoryElector._leaders["g2"] = "usurper"
        assert lost.wait(timeout=2)
        t.join(timeout=2)
        sel.stop()
        InMemoryElector._leaders.pop("g2", None)

    def test_selector_demote_releases_lease_and_fires_loss_once(self):
        """Fail-stop demotion (journal fsync death): the lease must be
        RELEASED — not silently kept warm by the heartbeat thread — so a
        standby acquires before any TTL runs out, and on_loss fires
        exactly once even when demote() is called again."""
        elector = InMemoryElector("g3", "x")
        losses = []
        sel = LeaderSelector(elector, poll_s=0.01,
                             on_loss=lambda: losses.append(1))
        sel.wait_for_leadership()
        t = sel.start_heartbeat_thread()
        sel.demote()
        assert not sel.is_leader
        standby = InMemoryElector("g3", "y")
        assert standby.try_acquire()
        t.join(timeout=2)
        assert not t.is_alive()  # no renewals after demotion
        sel.demote()
        assert losses == [1]
        InMemoryElector._leaders.pop("g3", None)

    def test_selector_concurrent_loss_fires_once(self):
        """demote() racing a heartbeat failure observes the loss from
        two threads at once: _fire_loss's test-and-set is atomic, so
        on_loss still runs exactly once."""
        elector = InMemoryElector("g4", "x")
        losses = []
        barrier = threading.Barrier(8)
        sel = LeaderSelector(elector, poll_s=0.01,
                             on_loss=lambda: losses.append(1))
        sel.wait_for_leadership()

        def fire():
            barrier.wait()
            sel._fire_loss()

        threads = [threading.Thread(target=fire) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=2)
        assert losses == [1]
        InMemoryElector._leaders.pop("g4", None)


class TestReactionWiring:
    """One flag governs BOTH halves of reaction (d): the REST 429 shed
    AND the scheduler's considerable-window scaleback."""

    @staticmethod
    def _build(load_shedding):
        from cook_tpu.utils.config import Settings
        s = Settings(clusters=[{
            "kind": "mock", "name": "m1",
            "hosts": [{"node_id": "h0", "mem": 4000, "cpus": 8}],
        }], pools=[{"name": "default"}], load_shedding=load_shedding,
            rank_interval_s=3600, match_interval_s=3600)
        return build_process(s, start_rest=False)

    def test_load_shedding_on_wires_admission_to_shedder(self):
        p = self._build(True)
        try:
            assert (p.scheduler.admission.overload_fn
                    == p.api.shedder.overloaded)
        finally:
            shutdown(p)

    def test_load_shedding_off_leaves_admission_inert(self):
        p = self._build(False)
        try:
            # no silent considerable-window shrink with the knob off
            assert p.scheduler.admission.overload_fn is None
            assert p.scheduler.admission.overloaded() is False
        finally:
            shutdown(p)


class TestConfig:
    def test_defaults(self):
        s = read_config(None)
        assert s.port == 12321
        assert s.match.max_jobs_considered == 1000

    def test_file_and_pool_schedulers(self, tmp_path):
        p = tmp_path / "c.json"
        p.write_text(json.dumps({
            "port": 4242,
            "pools": [{"name": "a"}, {"name": "b", "dru_mode": "gpu"}],
            "match": {"max_jobs_considered": 77},
            "pool_schedulers": [
                {"pool_regex": "b.*", "match": {"max_jobs_considered": 5}},
            ],
            "rebalancer": {"max_preemption": 9},
        }))
        s = read_config(str(p))
        assert s.port == 4242
        assert s.match_config_for_pool("a").max_jobs_considered == 77
        assert s.match_config_for_pool("bxx").max_jobs_considered == 5
        assert s.rebalancer.max_preemption == 9

    def test_gang_knobs_roundtrip(self, tmp_path):
        # every documented gang knob must survive the JSON loader — a
        # key the parser drops silently runs the service on defaults
        p = tmp_path / "g.json"
        p.write_text(json.dumps({
            "match": {"gang_enabled": False, "topology_weight": 0.5,
                      "topology_block_hosts": 2},
            "rebalancer": {"gang_enabled": False,
                           "gang_max_admissions": 7,
                           "gang_drain_max_wait_ms": 1000.0,
                           "gang_drain_wasted_factor": 2.5},
            "elastic": {"count_block_headroom": False,
                        "gang_block_hosts": 8},
            "api": {"max_gang_size": 16},
        }))
        s = read_config(str(p))
        assert s.match.gang_enabled is False
        assert s.match.topology_weight == 0.5
        assert s.match.topology_block_hosts == 2
        assert s.rebalancer.gang_enabled is False
        assert s.rebalancer.gang_max_admissions == 7
        assert s.rebalancer.gang_drain_max_wait_ms == 1000.0
        assert s.rebalancer.gang_drain_wasted_factor == 2.5
        assert s.elastic == {"count_block_headroom": False,
                             "gang_block_hosts": 8}
        assert s.api == {"max_gang_size": 16}

    def test_superblock_and_resident_knobs_roundtrip(self, tmp_path):
        # the mega-scale/residency knobs must survive the loader: the
        # superblock width (short key + long alias), the section-level
        # resident bools, and the top-level shorthands
        p = tmp_path / "sb.json"
        p.write_text(json.dumps({
            "match": {"hier_superblock_nodes": 8192},
            "rebalancer": {"resident": True},
            "elastic": {"resident": True},
        }))
        s = read_config(str(p))
        assert s.match.hierarchical_superblock_nodes == 8192
        assert s.rebalancer.resident is True
        assert s.elastic["resident"] is True

        p.write_text(json.dumps({
            "match": {"hierarchical_superblock_nodes": 4096},
            "resident_rebalancer": True,
            "resident_elastic": True,
        }))
        s = read_config(str(p))
        assert s.match.hierarchical_superblock_nodes == 4096
        assert s.rebalancer.resident is True
        assert s.elastic["resident"] is True

        # defaults stay off; an explicit section-level knob beats the
        # top-level shorthand
        s = read_config(None)
        assert s.match.hierarchical_superblock_nodes == 0
        assert s.rebalancer.resident is False
        p.write_text(json.dumps({
            "rebalancer": {"resident": False},
            "elastic": {"resident": False},
            "resident_rebalancer": True,
            "resident_elastic": True,
        }))
        s = read_config(str(p))
        assert s.rebalancer.resident is False
        assert s.elastic["resident"] is False

    def test_validation(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"port": -1}))
        with pytest.raises(ValueError):
            read_config(str(p))
        p.write_text(json.dumps({"pools": [{"name": "x"}, {"name": "x"}]}))
        with pytest.raises(ValueError):
            read_config(str(p))


def test_full_process_end_to_end(tmp_path):
    """Boot a whole node from config: REST + leader loops + mock cluster;
    submit through HTTP; watch the job complete as virtual cycles fire."""
    cfg = tmp_path / "config.json"
    cfg.write_text(json.dumps({
        "port": 0,  # replaced below
        "pools": [{"name": "default"}],
        "clusters": [{
            "kind": "mock",
            "name": "m1",
            "hosts": [{"node_id": "h1", "mem": 4000, "cpus": 8},
                      {"node_id": "h2", "mem": 4000, "cpus": 8}],
        }],
        "rank_interval_s": 3600,   # fire manually
        "match_interval_s": 3600,
    }))
    from cook_tpu.rest.server import free_port

    settings = read_config(str(cfg), {"port": free_port()})
    process = build_process(settings)
    try:
        # standby: not leader yet
        url = f"http://127.0.0.1:{settings.port}"
        r = requests.post(f"{url}/jobs", json={"jobs": [
            {"command": "x", "mem": 100, "cpus": 1, "expected_runtime": 1000}
        ]}, headers={"X-Cook-Requesting-User": "u1"})
        assert r.status_code == 201, r.text
        uuid = r.json()["jobs"][0]

        start_leader_duties(process, block=False,
                            on_loss=lambda: None)
        assert process.is_leader()
        # fire the cycles manually (loops are on 1h timers)
        loops = {l.name: l for l in process.loops}
        loops["rank"].fire()
        loops["match"].fire()
        r = requests.get(f"{url}/jobs/{uuid}",
                         headers={"X-Cook-Requesting-User": "u1"})
        assert r.json()["status"] == "running"
        # complete on the mock backend
        process.clusters[0].advance_to(process.store.clock() + 10_000_000)
        r = requests.get(f"{url}/jobs/{uuid}",
                         headers={"X-Cook-Requesting-User": "u1"})
        assert r.json()["status"] == "completed"
    finally:
        shutdown(process)


def test_two_processes_one_leader(tmp_path):
    """Hot standby: second process does not become leader while the first
    holds the lease (reference: test_master_slave)."""
    lease = str(tmp_path / "lease")
    from cook_tpu.rest.server import free_port
    from cook_tpu.utils.config import Settings

    s1 = Settings(port=free_port(), leader_lease_path=lease,
                  clusters=[], pools=[{"name": "default"}])
    s2 = Settings(port=free_port(), leader_lease_path=lease,
                  clusters=[], pools=[{"name": "default"}])
    p1 = build_process(s1, start_rest=False)
    p2 = build_process(s2, start_rest=False)
    try:
        start_leader_duties(p1, block=False, on_loss=lambda: None)
        assert p1.is_leader()
        got_leadership = threading.Event()

        def try2():
            p2.selector_thread_started = True
            start_leader_duties(p2, block=False, on_loss=lambda: None)
            got_leadership.set()

        t = threading.Thread(target=try2, daemon=True)
        t.start()
        time.sleep(0.3)
        assert not got_leadership.is_set()  # standby waits
        shutdown(p1)  # leader releases
        assert got_leadership.wait(timeout=15)
        assert p2.is_leader()
    finally:
        shutdown(p2)
        shutdown(p1)


def test_standby_proxies_queue_to_leader():
    from cook_tpu.models.entities import Pool
    from cook_tpu.models.store import JobStore
    from cook_tpu.rest.api import ApiConfig, CookApi
    from cook_tpu.rest.server import ServerThread

    store = JobStore(clock=lambda: 0)
    store.set_pool(Pool(name="default"))
    api = CookApi(store, None, ApiConfig())
    api.leader = False
    api.leader_url = "http://leader.example:12321"
    srv = ServerThread(api).start()
    try:
        r = requests.get(f"{srv.url}/queue", allow_redirects=False,
                         headers={"X-Cook-Requesting-User": "u"})
        assert r.status_code == 307
        assert r.headers["Location"] == "http://leader.example:12321/queue"
    finally:
        srv.stop()
