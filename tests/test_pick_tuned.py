"""tools/pick_tuned.py: the sweep -> tuned_match.json promotion that the
round-end bench consumes — selection, efficiency bar, resilience."""
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def run_pick(tmp_path, rows, min_eff=None):
    sweep = tmp_path / "sweep.jsonl"
    with open(sweep, "w") as f:
        for row in rows:
            f.write((row if isinstance(row, str) else json.dumps(row))
                    + "\n")
    out = tmp_path / "tuned.json"
    cmd = [sys.executable, str(REPO / "tools" / "pick_tuned.py"),
           "--sweep", str(sweep), "--out", str(out)]
    if min_eff is not None:
        cmd += ["--min-eff", str(min_eff)]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    return proc, (json.load(open(out)) if out.exists() else None)


def record(backend="xla", chunk=1024, passes=2, rounds=3, kc=128,
           p50=500.0, eff=1.0, platform="tpu"):
    return {"platform": platform, "backend": backend, "chunk": chunk,
            "passes": passes, "rounds": rounds, "kc": kc,
            "p50_ms": p50, "packing_eff": eff}


def test_picks_lowest_p50_above_bar(tmp_path):
    proc, tuned = run_pick(tmp_path, [
        record(p50=700, eff=1.004),
        record(backend="bucketed", p50=250, eff=0.997),
        record(backend="pallas", p50=150, eff=0.985),  # below the bar
        record(p50=400, eff=0.991),                    # below 0.995 bar
    ], min_eff=0.995)
    assert proc.returncode == 0
    assert tuned["backend"] == "bucketed"
    assert tuned["measured_p50_ms"] == 250


def test_ignores_cpu_started_and_error_records(tmp_path):
    proc, tuned = run_pick(tmp_path, [
        record(p50=100, eff=1.0, platform="cpu"),  # cpu fallback: excluded
        {"backend": "xla", "chunk": 1024, "passes": 2, "rounds": 3,
         "kc": 128, "started": True},
        {"backend": "pallas", "chunk": 8192, "passes": 8, "rounds": 1,
         "kc": 1, "error": "abandoned after 2 hung attempts"},
        '{"truncated": ',  # killed writer mid-line
        record(p50=600, eff=1.002),
    ])
    assert proc.returncode == 0
    assert tuned["measured_p50_ms"] == 600


def test_no_qualifying_config_keeps_defaults(tmp_path):
    proc, tuned = run_pick(tmp_path, [record(p50=100, eff=0.9)])
    assert proc.returncode == 1
    assert tuned is None
    # bench falls back to its built-in default when the file is absent
