"""Trace replay over the k8s-style backend: the expected-vs-actual
controller handles the full workload (synthesized offers, pod lifecycle,
deletes) driven by the real scheduler cycles."""
from cook_tpu.cluster.k8s import FakeKubeApi, KubeCluster, KubeNode, PodPhase
from cook_tpu.models.entities import JobState, Pool, Resources, Job
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler
from cook_tpu.sim.simulator import synth_trace
from tests.conftest import FakeClock


def test_k8s_trace_replay():
    jobs, hosts = synth_trace(150, 0, n_users=8, seed=3,
                              mean_runtime_ms=60_000,
                              submit_span_ms=120_000)
    clock = FakeClock()
    api = FakeKubeApi([
        KubeNode(name=f"n{i}", mem=64000, cpus=32) for i in range(10)
    ])
    cluster = KubeCluster("k8s", api, clock)
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    scheduler = Scheduler(store, [cluster])
    pool = store.pools["default"]

    submitted = 0
    trace = sorted(jobs, key=lambda j: (j.submit_time_ms, j.uuid))
    ends: dict[str, int] = {}
    for cycle in range(300):
        # pod lifecycle: pending pods start; running pods past their
        # job's runtime finish
        api.tick()
        for pod in list(api.list_pods()):
            if pod.phase == PodPhase.RUNNING:
                end = ends.get(pod.name)
                if end is not None and end <= clock():
                    api.finish_pod(pod.name)
        # submissions
        while (submitted < len(trace)
               and trace[submitted].submit_time_ms <= clock()):
            tj = trace[submitted]
            store.submit_jobs([Job(
                uuid=tj.uuid, user=tj.user, pool="default",
                resources=Resources(mem=tj.mem, cpus=tj.cpus),
                expected_runtime_ms=tj.runtime_ms, command="sim",
                max_retries=5,
            )])
            submitted += 1
        scheduler.rank_cycle(pool)
        outcome = scheduler.match_cycle(pool)
        for job, _offer in outcome.matched:
            [tid] = [i.task_id for i in store.job_instances(job.uuid)
                     if not i.status.terminal]
            ends[tid] = clock() + job.expected_runtime_ms
        clock.advance(15_000)
        if submitted == len(trace) and all(
            store.jobs[j.uuid].state == JobState.COMPLETED for j in jobs
        ):
            break
    assert all(
        store.jobs[j.uuid].state == JobState.COMPLETED for j in jobs
    ), {store.jobs[j.uuid].state for j in jobs}
    # the backend is clean: no task pods left
    assert not [p for p in api.list_pods() if not p.synthetic]
    # controller agreed with store throughout: no stranded expectations
    live_expected = {t for t, s in cluster.expected.items()
                     if s.value in ("starting", "running")}
    assert not live_expected
