"""Sharded control plane: router, partitioned store, commit pipeline.

Covers the ISSUE-14 tentpole invariants at the unit level: deterministic
routing (pool first, hashed-user fallback), the ShardedStore facade
(merged views, pool-scoped routing, broadcast pool metadata, meta-shard
globals), cross-shard pool moves as an ordered two-shard apply, and the
ShardedTransactionLog's per-shard idempotency + all-or-nothing vetoes.
"""
import pytest

from cook_tpu.models.entities import (InstanceStatus, Job, JobState, Pool,
                                      Quota, Resources, Share)
from cook_tpu.models.store import TransactionVetoed
from cook_tpu.shard import ShardedStore, ShardedTransactionLog, ShardRouter
from cook_tpu.shard.router import META_SHARD


def job(uuid, pool, user="u0", **kw):
    return Job(uuid=uuid, user=user, pool=pool, command="true",
               resources=Resources(mem=64, cpus=1), **kw)


@pytest.fixture
def plane():
    store = ShardedStore(4)
    router = store.router
    pools = router.pools_for_distinct_shards()
    for name in pools:
        store.set_pool(Pool(name=name))
    txn = ShardedTransactionLog(store)
    return store, txn, router, pools


# ---------------------------------------------------------------- router


def test_router_is_deterministic_and_stable():
    a, b = ShardRouter(8), ShardRouter(8)
    for pool in ("prod", "dev", "gpu-a", "gpu-b"):
        assert a.shard_for_pool(pool) == b.shard_for_pool(pool)
    for user in ("alice", "bob"):
        assert a.shard_for_user(user) == b.shard_for_user(user)


def test_router_distinct_pool_helper():
    router = ShardRouter(4)
    pools = router.pools_for_distinct_shards()
    shards = [router.shard_for_pool(p) for p in pools]
    assert sorted(shards) == [0, 1, 2, 3]


def test_router_plan_routes_by_pool_and_falls_back_to_user(plane):
    store, _, router, pools = plane
    plan = router.plan("jobs/submit", {"jobs": [job("a", pools[2])]},
                       store)
    assert plan.single == router.shard_for_pool(pools[2])
    # unknown job uuid: pool-less key -> hashed-user fallback, still
    # deterministic so the veto lands on one consistent shard
    plan = router.plan("job/retry", {"uuid": "nope"}, store)
    assert plan.single == router.shard_for_user("nope")
    # global ops own the meta shard
    assert router.plan("config/update", {"updates": {}},
                       store).single == META_SHARD


# ----------------------------------------------------------------- store


def test_sharded_store_partitions_and_merges(plane):
    store, txn, router, pools = plane
    uuids = []
    for i in range(12):
        u = f"j{i:02d}"
        uuids.append(u)
        txn.commit("jobs/submit", {"jobs": [job(u, pools[i % 4])]})
    # every shard owns exactly its pools' jobs
    for i, shard in enumerate(store.shards):
        for u in shard.jobs:
            assert router.shard_for_pool(shard.jobs[u].pool) == i
    assert len(store.jobs) == 12
    assert sorted(store.jobs.keys()) == uuids
    assert "j03" in store.jobs
    assert store.jobs["j03"].pool == pools[3]
    # pool-scoped reads route to one shard and see only its jobs
    assert {j.uuid for j in store.pending_jobs(pools[1])} == {
        "j01", "j05", "j09"}
    assert store.pending_count(pools[1]) == 3
    assert store.pending_count() == 12


def test_pool_metadata_broadcasts_and_meta_shard_owns_globals(plane):
    store, txn, _, pools = plane
    for shard in store.shards:
        assert set(shard.pools) == set(pools)
    txn.commit("config/update", {"updates": {"k": 1}})
    assert store.dynamic_config == {"k": 1}
    assert store.shards[META_SHARD].dynamic_config == {"k": 1}
    for i, shard in enumerate(store.shards):
        if i != META_SHARD:
            assert shard.dynamic_config == {}
    outcome = txn.commit("pool/capacity-delta", {"moves": [
        {"kind": "loan", "from": pools[0], "to": pools[1],
         "mem": 100.0}]})
    assert outcome.result["applied"] == 1
    assert store.encoded_capacity_ledger()[0]["mem"] == 100.0


def test_share_quota_route_by_pool(plane):
    store, txn, router, pools = plane
    txn.commit("share/set", {"share": Share(
        user="alice", pool=pools[2],
        resources=Resources(mem=10, cpus=1, gpus=0))})
    owner = store.shards[router.shard_for_pool(pools[2])]
    assert ("alice", pools[2]) in owner.shares
    assert store.get_share("alice", pools[2]).mem == 10
    txn.commit("quota/set", {"quota": Quota(
        user="alice", pool=pools[2],
        resources=Resources(mem=5, cpus=1, gpus=0), count=3)})
    assert store.get_quota("alice", pools[2]).count == 3


def test_instance_lifecycle_routes_by_owning_shard(plane):
    store, txn, router, pools = plane
    txn.commit("jobs/submit", {"jobs": [job("run-me", pools[3])]})
    inst = store.create_instance("run-me", "task-1", hostname="h0")
    owner = store.shards[router.shard_for_pool(pools[3])]
    assert inst.task_id in owner.instances
    assert store.jobs["run-me"].state is JobState.RUNNING
    assert [j.uuid for j in store.running_jobs(pools[3])] == ["run-me"]
    update = store.update_instance_state("task-1",
                                         InstanceStatus.SUCCESS)
    assert update.applied
    assert store.jobs["run-me"].state is JobState.COMPLETED
    assert store.job_instances("run-me")[0].status is \
        InstanceStatus.SUCCESS


# ------------------------------------------------------ cross-shard moves


def test_cross_shard_pool_move(plane):
    store, txn, router, pools = plane
    src_pool, dst_pool = pools[0], pools[3]
    txn.commit("jobs/submit", {"jobs": [job("mover", src_pool)]})
    outcome = txn.commit("job/pool-move",
                         {"uuid": "mover", "pool": dst_pool})
    assert outcome.result["moved"] is True
    assert set(outcome.shard_seqs) == {router.shard_for_pool(src_pool),
                                       router.shard_for_pool(dst_pool)}
    src = store.shards[router.shard_for_pool(src_pool)]
    dst = store.shards[router.shard_for_pool(dst_pool)]
    assert "mover" not in src.jobs
    assert dst.jobs["mover"].pool == dst_pool
    assert [j.uuid for j in store.pending_jobs(dst_pool)] == ["mover"]
    assert store.pending_jobs(src_pool) == []
    # the source shard's own journal feed carries the shard-out, the
    # destination's the upsert — per-segment replay stays self-contained
    src_kinds = [e.kind for e in src.events_since(0)]
    dst_kinds = [e.kind for e in dst.events_since(0)]
    assert "job/shard-out" in src_kinds
    assert "job/pool-moved" in dst_kinds


def test_cross_shard_move_only_moves_waiting_jobs(plane):
    store, txn, router, pools = plane
    txn.commit("jobs/submit", {"jobs": [job("busy", pools[0])]})
    store.create_instance("busy", "t-busy", hostname="h0")
    outcome = txn.commit("job/pool-move",
                         {"uuid": "busy", "pool": pools[3]})
    assert outcome.result["moved"] is False
    assert store.jobs["busy"].pool == pools[0]


# ------------------------------------------------------------ txn pipeline


def test_idempotent_replay_single_and_cross_shard(plane):
    store, txn, router, pools = plane
    first = txn.commit("jobs/submit", {"jobs": [job("one", pools[1])]},
                       txn_id="t-1")
    replay = txn.commit("jobs/submit", {"jobs": [job("one", pools[1])]},
                        txn_id="t-1")
    assert not first.duplicate and replay.duplicate
    assert replay.result == first.result
    # cross-shard submit: one txn spanning two shards dedupes from
    # EITHER shard's idempotency table
    batch = [job("x-a", pools[0]), job("x-b", pools[2])]
    first = txn.commit("jobs/submit", {"jobs": batch}, txn_id="t-2")
    assert len(first.shard_seqs) == 2
    replay = txn.commit("jobs/submit", {"jobs": batch}, txn_id="t-2")
    assert replay.duplicate
    # the duplicate answer reconstructs the PER-SHARD seq vector from
    # each shard's sealed record — batch replication waits must never
    # misattribute the coordinator's seq to shard 0
    assert replay.shard_seqs == first.shard_seqs
    assert len(store.jobs) == 3
    for i in first.shard_seqs:
        assert "t-2" in store.shards[i].txn_results


def test_cross_shard_submit_veto_is_all_or_nothing(plane):
    store, txn, _, pools = plane
    txn.commit("jobs/submit", {"jobs": [job("taken", pools[2])]})
    with pytest.raises(TransactionVetoed):
        txn.commit("jobs/submit", {"jobs": [
            job("fresh", pools[0]), job("taken", pools[2])]})
    # the veto on the second shard must not leave the first shard's half
    assert "fresh" not in store.jobs


def test_concurrent_cross_shard_commits_do_not_deadlock(plane):
    """Ordered lock acquisition (ascending shard ids) + planned-shard
    discipline: concurrent cross-shard moves/kills/submits interleave
    without deadlock and every job ends owned by exactly one shard."""
    import threading

    store, txn, router, pools = plane
    n = 24
    txn.commit("jobs/submit", {"jobs": [
        job(f"c{i:02d}", pools[i % 4]) for i in range(n)]})
    errors = []

    def mover(offset):
        try:
            for i in range(offset, n, 2):
                txn.commit("job/pool-move",
                           {"uuid": f"c{i:02d}",
                            "pool": pools[(i + offset + 1) % 4]})
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    def killer():
        try:
            txn.commit("jobs/kill",
                       {"uuids": [f"c{i:02d}" for i in range(0, n, 3)]})
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=mover, args=(0,)),
               threading.Thread(target=mover, args=(1,)),
               threading.Thread(target=killer)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "cross-shard commit deadlocked"
    assert not errors, errors
    assert len(store.jobs) == n
    for i in range(n):
        owners = [s.shard_id for s in store.shards
                  if f"c{i:02d}" in s.jobs]
        assert len(owners) == 1, (i, owners)
        owner_pool = store.jobs[f"c{i:02d}"].pool
        assert router.shard_for_pool(owner_pool) == owners[0]


def test_cross_shard_kill_and_user_views(plane):
    store, txn, _, pools = plane
    batch = [job(f"k{i}", pools[i % 4], user="killer") for i in range(4)]
    txn.commit("jobs/submit", {"jobs": batch})
    outcome = txn.commit("jobs/kill",
                         {"uuids": [f"k{i}" for i in range(4)]})
    assert sorted(outcome.result["killed"]) == [f"k{i}" for i in range(4)]
    assert all(j.state is JobState.COMPLETED
               for j in store.user_jobs("killer"))
    assert len(outcome.shard_seqs) == 4
