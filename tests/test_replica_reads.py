"""Replica-served reads: the bounded-staleness contract (ISSUE 14).

A non-leader replica serves heavy reads off its replayed per-shard
journals with:

  * `X-Cook-Staleness-Ms` (worst shard) + `X-Cook-Shard-Staleness`
    (per-shard split) on every replica-served read, and a
    `staleness_ms` field in JSON-object bodies;
  * staleness MONOTONE per shard while the replica is behind;
  * leader fallback (307) above the freshness ceiling;
  * refusal (503) when the replica stops applying — never served
    arbitrarily stale forever.
"""
import http.client
import json
import time
import urllib.parse

import pytest

from cook_tpu import faults
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import InprocessControlPlane, ServerThread
from cook_tpu.shard import ShardedStore
from cook_tpu.shard.replica import (ShardedJournalFollower,
                                    evaluate_staleness)

N_SHARDS = 2


def raw_get(url: str, path: str):
    """(status, headers, body) WITHOUT following redirects."""
    parsed = urllib.parse.urlparse(url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=10)
    try:
        conn.request("GET", path,
                     headers={"X-Cook-Requesting-User": "admin"})
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, dict(resp.getheaders()), body
    finally:
        conn.close()


@pytest.fixture
def rig():
    leader = InprocessControlPlane(shards=N_SHARDS,
                                   pools=("pool0", "pool1")).start()
    store2 = ShardedStore(N_SHARDS)
    follower = ShardedJournalFollower(
        store2, leader_url_fn=lambda: leader.url,
        self_url="http://replica", member_id="replica",
        poll_s=0.05, timeout_s=2.0, long_poll_s=0.1).start()
    api2 = CookApi(store2, None, ApiConfig())
    api2.leader = False
    api2.leader_url = leader.url
    api2.staleness_fn = follower.staleness_view
    replica = ServerThread(api2).start()
    try:
        yield leader, replica, api2, follower, store2
    finally:
        faults.disarm()
        follower.stop()
        replica.stop()
        leader.stop()


def submit(leader, uuid, pool):
    import urllib.request

    req = urllib.request.Request(
        f"{leader.url}/jobs",
        data=json.dumps({"jobs": [{"uuid": uuid, "command": "true",
                                   "mem": 64, "cpus": 0.1,
                                   "pool": pool}]}).encode(),
        headers={"X-Cook-Requesting-User": "admin",
                 "Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 201


def wait_until(pred, timeout_s=10.0, what="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def shard_staleness(headers) -> dict:
    return json.loads(headers["X-Cook-Shard-Staleness"])


def test_replica_serves_with_staleness_headers_and_field(rig):
    leader, replica, api2, follower, store2 = rig
    submit(leader, "r-0", "pool0")
    submit(leader, "r-1", "pool1")
    wait_until(lambda: "r-0" in store2.jobs and "r-1" in store2.jobs,
               what="replica sync")
    status, headers, body = raw_get(replica.url, "/jobs/r-1")
    assert status == 200
    staleness = headers["X-Cook-Staleness-Ms"]
    assert staleness != "inf" and int(staleness) < 60_000
    per_shard = shard_staleness(headers)
    assert set(per_shard) == {"0", "1"}
    payload = json.loads(body)
    assert payload["uuid"] == "r-1"
    assert "staleness_ms" in payload
    # /debug/* is stamped too (served, never redirected)
    status, headers, _ = raw_get(replica.url, "/debug/contention")
    assert status == 200 and "X-Cook-Staleness-Ms" in headers
    # the leader never stamps staleness: its reads are authoritative
    status, headers, _ = raw_get(leader.url, "/jobs/r-1")
    assert status == 200 and "X-Cook-Staleness-Ms" not in headers


def test_staleness_is_monotone_per_shard_while_behind(rig):
    leader, replica, api2, follower, store2 = rig
    submit(leader, "m-0", "pool0")
    wait_until(lambda: "m-0" in store2.jobs, what="replica sync")
    # cut replication: the replica's freshness proof stops refreshing
    faults.arm(faults.FaultSchedule([faults.FaultRule(
        point=faults.REPLICATION_FETCH, mode="error")]))
    submit(leader, "m-1", "pool0")
    time.sleep(0.2)
    _, headers_a, _ = raw_get(replica.url, "/jobs/m-0")
    time.sleep(0.3)
    _, headers_b, _ = raw_get(replica.url, "/jobs/m-0")
    a, b = shard_staleness(headers_a), shard_staleness(headers_b)
    for shard in a:
        assert b[shard] >= a[shard], (a, b)
    assert int(headers_b["X-Cook-Staleness-Ms"]) > \
        int(headers_a["X-Cook-Staleness-Ms"])


def test_replica_that_stops_applying_refuses_reads(rig):
    leader, replica, api2, follower, store2 = rig
    submit(leader, "s-0", "pool0")
    wait_until(lambda: "s-0" in store2.jobs, what="replica sync")
    faults.arm(faults.FaultSchedule([faults.FaultRule(
        point=faults.REPLICATION_FETCH, mode="error")]))
    api2.config.replica_refuse_after_s = 0.05
    time.sleep(0.3)  # several failed polls: stalled_s passes the bound
    status, _, body = raw_get(replica.url, "/jobs/s-0")
    assert status == 503
    assert b"stopped applying" in body
    # /debug/replica names the decision
    status, _, body = raw_get(replica.url, "/debug/replica")
    assert json.loads(body)["decision"]["action"] == "refuse"


def test_staleness_over_ceiling_falls_back_to_leader(rig):
    leader, replica, api2, follower, store2 = rig
    submit(leader, "f-0", "pool1")
    wait_until(lambda: "f-0" in store2.jobs, what="replica sync")
    # ceiling below any possible staleness: every gated read redirects
    api2.config.replica_staleness_ceiling_ms = -1.0
    status, headers, _ = raw_get(replica.url, "/jobs/f-0")
    assert status == 307
    assert headers["Location"].startswith(leader.url)
    assert headers["Location"].endswith("/jobs/f-0")
    # back under the ceiling: served locally again
    api2.config.replica_staleness_ceiling_ms = 60_000.0
    status, headers, _ = raw_get(replica.url, "/jobs/f-0")
    assert status == 200 and "X-Cook-Staleness-Ms" in headers


def test_evaluate_staleness_decision_table():
    fresh = {0: {"staleness_ms": 10.0, "stalled_s": 0.1},
             1: {"staleness_ms": 40.0, "stalled_s": 0.1}}
    verdict = evaluate_staleness(fresh, ceiling_ms=100.0,
                                 refuse_after_s=30.0)
    assert verdict["action"] == "serve"
    assert verdict["staleness_ms"] == 40.0
    over = {**fresh, 1: {"staleness_ms": 500.0, "stalled_s": 0.1}}
    assert evaluate_staleness(over, ceiling_ms=100.0,
                              refuse_after_s=30.0)["action"] == "fallback"
    stalled = {**fresh, 1: {"staleness_ms": 50.0, "stalled_s": 90.0}}
    assert evaluate_staleness(stalled, ceiling_ms=100.0,
                              refuse_after_s=30.0)["action"] == "refuse"
    # never-synced but actively polling (fresh standby catching up a
    # backlog): fall back to the leader — reads stay available through
    # restarts; never served locally (staleness is unbounded)
    catching_up = {0: {"staleness_ms": float("inf"), "stalled_s": 0.1}}
    assert evaluate_staleness(catching_up, ceiling_ms=1e12,
                              refuse_after_s=30.0)["action"] == "fallback"
    # never synced AND not polling either: refuse outright
    never = {0: {"staleness_ms": float("inf"),
                 "stalled_s": float("inf")}}
    assert evaluate_staleness(never, ceiling_ms=1e12,
                              refuse_after_s=1e12)["action"] == "refuse"
