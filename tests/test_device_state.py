"""Device-resident match state (scheduler/device_state.py +
ops/device_update.py): warm-cycle transfer floor, O(delta) donated-buffer
updates, invalidation/rebuild ladder, quantization parity guard, the
offers_fingerprint contract, and the fused fine-pass scorer."""
import numpy as np
import pytest

import jax.numpy as jnp

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Job, Pool, Resources
from cook_tpu.models.store import JobStore
from cook_tpu.obs import data_plane
from cook_tpu.scheduler import encode_cache as encode_cache_mod
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.device_state import (
    DeviceResidentState,
    quantized_dtype,
    snapshot_all,
)
from cook_tpu.scheduler.encode_cache import EncodeCache, offers_fingerprint
from cook_tpu.scheduler.matcher import MatchConfig

from conftest import FakeClock, make_job


ENCODE_FAMS = (data_plane.FAM_NODE_ENCODE, data_plane.FAM_FEASIBILITY)


def encode_h2d():
    totals = data_plane.LEDGER.family_totals()
    return sum(totals.get(f, {}).get("h2d_bytes", 0) for f in ENCODE_FAMS)


def resident_rig(n_jobs=200, n_hosts=8, host_mem=4096.0, *,
                 resident=True, quantized=False, telemetry=False,
                 chunk=0, job_mem=4000.0, **sched_kw):
    """Scheduler + near-host-size jobs: a handful match on the cold
    cycle, the rest wait — warm cycles then see an unchanged pool."""
    store = JobStore(clock=lambda: 1_000_000)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m",
        [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=host_mem,
                  cpus=8.0) for i in range(n_hosts)],
        clock=store.clock)
    config = SchedulerConfig(
        match=MatchConfig(chunk=chunk, device_residency=resident,
                          quantized=quantized, quality_audit_every=0),
        device_telemetry=telemetry, **sched_kw)
    scheduler = Scheduler(store, [cluster], config)
    store.submit_jobs([
        Job(uuid=f"j{i}", user=f"u{i % 4}", pool="default", priority=50,
            resources=Resources(mem=job_mem, cpus=8.0), command="true")
        for i in range(n_jobs)
    ])
    return store, scheduler


def run_cycle(store, scheduler):
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    record = scheduler.recorder.records(limit=1)[0]
    return outcome, record


# --------------------------------------------------- warm-cycle transfers


def test_warm_cycles_cut_encode_h2d_by_90_percent():
    """THE acceptance bar: with residency enabled, a warm unchanged-pool
    cycle moves >= 90% fewer node-encode + job-feasibility H2D bytes
    than the cold rebuild cycle (PR 11 TransferLedger stamps)."""
    store, scheduler = resident_rig(n_jobs=1000, n_hosts=16)
    m0 = encode_h2d()
    _, r_cold = run_cycle(store, scheduler)
    cold = encode_h2d() - m0
    assert r_cold.device_state["rebuild"] is True
    assert r_cold.device_state["reason"] == "cold"
    for _ in range(2):
        m0 = encode_h2d()
        _, r_warm = run_cycle(store, scheduler)
        warm = encode_h2d() - m0
        assert r_warm.device_state["rebuild"] is False
        assert r_warm.device_state["delta_rows"] == 0
        assert warm <= 0.1 * cold, (warm, cold)


def test_resident_placements_identical_to_classic_path():
    """Residency is a transfer optimization, never a decision change:
    serial cycles match identical (job, host) pairs with it on or off."""
    def matched(resident):
        store, scheduler = resident_rig(n_jobs=60, n_hosts=6,
                                        job_mem=900.0, host_mem=4096.0,
                                        resident=resident)
        out = []
        for _ in range(3):
            outcome, _ = run_cycle(store, scheduler)
            out.append(sorted((j.uuid, o.hostname)
                              for j, o in outcome.matched))
        return out

    assert matched(True) == matched(False)


def test_single_new_job_is_one_delta_row():
    store, scheduler = resident_rig()
    run_cycle(store, scheduler)
    run_cycle(store, scheduler)
    store.submit_jobs([Job(uuid="delta", user="d", pool="default",
                           priority=50,
                           resources=Resources(mem=4000.0, cpus=8.0),
                           command="true")])
    _, record = run_cycle(store, scheduler)
    assert record.device_state["rebuild"] is False
    assert record.device_state["delta_rows"] == 1


def test_row_invalidation_re_uploads_only_that_row():
    """An instance/status event drops the job's feasibility rows (host
    cache AND mirror slot, via the subscriber): the next cycle scatters
    exactly the invalidated rows, no rebuild."""
    store, scheduler = resident_rig(n_jobs=40, job_mem=900.0)
    outcome, _ = run_cycle(store, scheduler)
    assert outcome.matched
    run_cycle(store, scheduler)
    # fail one matched instance: the job re-queues and its rows drop
    from cook_tpu.models.entities import InstanceStatus

    job, _offer = outcome.matched[0]
    inst = store.job_instances(job.uuid)[0]
    store.update_instance_state(inst.task_id, InstanceStatus.FAILED,
                                "preempted-by-rebalancer")
    _, record = run_cycle(store, scheduler)
    assert record.device_state["rebuild"] is False
    assert record.device_state["delta_rows"] >= 1
    assert record.device_state["delta_rows"] <= 3


def test_epoch_bump_forces_clean_rebuild():
    from cook_tpu.models.entities import Quota

    store, scheduler = resident_rig()
    run_cycle(store, scheduler)
    _, r_warm = run_cycle(store, scheduler)
    assert r_warm.device_state["rebuild"] is False
    store.set_quota(Quota(user="u0", pool="default",
                          resources=Resources(mem=10_000.0, cpus=100.0),
                          count=1000))
    _, record = run_cycle(store, scheduler)
    assert record.device_state["rebuild"] is True
    assert record.device_state["reason"] == "epoch-bumped"


def test_offer_structure_change_forces_rebuild():
    store, scheduler = resident_rig(n_hosts=4)
    run_cycle(store, scheduler)
    host = MockHost(node_id="grow", hostname="grow", mem=4096.0, cpus=8.0)
    scheduler.clusters[0].hosts[host.node_id] = host
    _, record = run_cycle(store, scheduler)
    assert record.device_state["rebuild"] is True
    assert record.device_state["reason"] == "offers-changed"


def test_job_bucket_growth_forces_rebuild():
    store, scheduler = resident_rig(n_jobs=60)
    _, r = run_cycle(store, scheduler)
    cap = r.device_state["resident_bytes"]
    # push the considerable window past the padded job bucket (64 -> 128)
    store.submit_jobs([
        Job(uuid=f"grow{i}", user="g", pool="default", priority=50,
            resources=Resources(mem=4000.0, cpus=8.0), command="true")
        for i in range(30)
    ])
    _, record = run_cycle(store, scheduler)
    assert record.device_state["rebuild"] is True
    assert record.device_state["reason"] == "bucket-growth"
    assert record.device_state["resident_bytes"] > cap


# ------------------------------------------------ compile-program pinning


def test_delta_updates_stay_on_one_program_per_bucket():
    """The CompileObservatory inducing test: delta sizes 1..4 share ONE
    update bucket (UPDATE_BUCKET_MIN=8), so the donated-buffer scatter
    compiles exactly one program per resident buffer — not one per
    delta size."""
    store, scheduler = resident_rig(n_jobs=40, telemetry=True)
    run_cycle(store, scheduler)
    observatory = scheduler.telemetry.observatory

    def submit(k, tag):
        store.submit_jobs([
            Job(uuid=f"{tag}-{i}", user="d", pool="default", priority=50,
                resources=Resources(mem=4000.0, cpus=8.0), command="true")
            for i in range(k)
        ])

    programs = []
    for delta, tag in ((1, "a"), (2, "b"), (3, "c"), (4, "d")):
        submit(delta, tag)
        _, record = run_cycle(store, scheduler)
        assert record.device_state["rebuild"] is False
        assert record.device_state["delta_rows"] == delta
        stats = observatory.stats()
        programs.append(stats["device_update"]["programs"])
    # 2 resident buffers (demands + feasibility) x 1 bucket = 2 programs,
    # STABLE across every delta size
    assert programs[0] == programs[-1] == 2, programs


# -------------------------------------------------- fingerprint contract


def test_offers_fingerprint_deterministic_and_order_sensitive():
    """Identical offer sets (fresh objects) fingerprint identically;
    DIFFERENT ARRIVAL ORDER fingerprints differently — feasibility rows
    are node-indexed in offer order, so order IS structure and a
    reordered set must never serve another order's cached rows."""
    def offers(order):
        cluster = MockCluster(
            "m",
            [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=100.0,
                      cpus=1.0) for i in order],
            clock=lambda: 0)
        return [(cluster, o) for o in cluster.pending_offers("default")]

    fp_a = offers_fingerprint(offers([0, 1, 2]))
    fp_b = offers_fingerprint(offers([0, 1, 2]))
    fp_c = offers_fingerprint(offers([2, 1, 0]))
    assert fp_a == fp_b
    assert fp_a != fp_c


def test_fingerprint_collision_with_different_node_count_rebuilds(
        monkeypatch):
    """Collision-shaped regression: even if offers_fingerprint COLLIDES
    across a node-count change, both the host cache (row-shape check)
    and the device mirror (n_real/n_pad key) must refuse the stale
    state and rebuild."""
    monkeypatch.setattr(encode_cache_mod, "offers_fingerprint",
                        lambda cluster_offers: 42)
    store, scheduler = resident_rig(n_hosts=4, n_jobs=30)
    _, r1 = run_cycle(store, scheduler)
    assert r1.device_state["rebuild"] is True
    _, r2 = run_cycle(store, scheduler)
    assert r2.device_state["rebuild"] is False  # collision-keyed warm hit
    for i in range(3):
        host = MockHost(node_id=f"x{i}", hostname=f"x{i}", mem=4096.0,
                        cpus=8.0)
        scheduler.clusters[0].hosts[host.node_id] = host
    out3, r3 = run_cycle(store, scheduler)
    assert r3.device_state["rebuild"] is True
    assert r3.device_state["reason"] == "offers-changed"
    # the rebuilt problem is shaped for the REAL node count: the three
    # fresh hosts are matchable this very cycle
    assert {o.hostname for _, o in out3.matched} == {"x0", "x1", "x2"}


# ----------------------------------------------------- encode-cache hook


def test_encode_cache_subscriber_callbacks():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cache = EncodeCache(store)
    events = []
    cache.subscribe(lambda kind, **info: events.append((kind, info)))
    job = make_job()
    store.submit_jobs([job])
    from cook_tpu.models.entities import InstanceStatus, Quota

    store.create_instance(job.uuid, "t1", hostname="h", node_id="n",
                          compute_cluster="c")
    store.update_instance_state("t1", InstanceStatus.FAILED, "failed")
    assert ("row-dropped", {"job_uuid": job.uuid}) in events
    store.set_quota(Quota(user="u", pool="default",
                          resources=Resources(mem=1.0, cpus=1.0), count=1))
    assert any(kind == "epoch-bumped" for kind, _ in events)


def test_subscriber_failure_never_blocks_events():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cache = EncodeCache(store)

    def bad(kind, **info):
        raise RuntimeError("sick subscriber")

    seen = []
    cache.subscribe(bad)
    cache.subscribe(lambda kind, **info: seen.append(kind))
    cache.clear()
    assert "epoch-bumped" in seen


# -------------------------------------------------------- quantization


def test_quantized_parity_holds_and_matches_f32_decisions():
    """Packing-efficiency parity of the quantized path vs f32 >= 0.98
    (here: identical placements on the seeded problem — parity 1.0)."""
    def matched(quantized):
        store, scheduler = resident_rig(n_jobs=80, job_mem=700.0,
                                        host_mem=8192.0,
                                        quantized=quantized)
        outcome, record = run_cycle(store, scheduler)
        if quantized:
            assert record.device_state["quantized"] is True
        return sorted((j.uuid, o.hostname) for j, o in outcome.matched)

    q, f = matched(True), matched(False)
    assert len(q) >= 0.98 * len(f)
    assert q == f  # at this shape bf16 rounding changes nothing


def test_quality_drift_demotes_quantized_pool_to_f32():
    """The drift-inducing test: a QualityMonitor sample under the
    parity floor demotes the pool — the next cycle rebuilds the mirror
    at f32 (reason dtype-changed) and stays f32."""
    store, scheduler = resident_rig(n_jobs=40, quantized=True,
                                    telemetry=True)
    _, r1 = run_cycle(store, scheduler)
    assert r1.device_state["quantized"] is True
    # the guard rides the monitor's sample feed (one wiring site covers
    # every match path)
    scheduler.telemetry.quality.record_sample("default", 0.5)
    assert scheduler.device_state.demoted_pools() == ["default"]
    _, r2 = run_cycle(store, scheduler)
    assert r2.device_state["quantized"] is False
    assert r2.device_state["rebuild"] is True
    assert r2.device_state["reason"] == "dtype-changed"
    _, r3 = run_cycle(store, scheduler)
    assert r3.device_state["quantized"] is False
    assert r3.device_state["rebuild"] is False


def test_healthy_quality_sample_never_demotes():
    store, scheduler = resident_rig(n_jobs=20, quantized=True,
                                    telemetry=True)
    run_cycle(store, scheduler)
    scheduler.telemetry.quality.record_sample("default", 0.995)
    assert scheduler.device_state.demoted_pools() == []


# ------------------------------------------------- multi-path + the sim


def test_pipelined_and_batched_paths_share_the_mirror():
    def run(mode):
        store = JobStore(clock=lambda: 1_000_000)
        hosts = []
        for p in range(2):
            store.set_pool(Pool(name=f"pool{p}"))
            hosts += [MockHost(node_id=f"p{p}h{i}", hostname=f"p{p}h{i}",
                               mem=8192.0, cpus=16.0, pool=f"pool{p}")
                      for i in range(3)]
        cluster = MockCluster("m", hosts, clock=store.clock)
        scheduler = Scheduler(store, [cluster], SchedulerConfig(
            match=MatchConfig(chunk=0, device_residency=True,
                              quality_audit_every=0),
            device_telemetry=False))
        store.submit_jobs([
            Job(uuid=f"j{p}-{i}", user=f"u{i % 3}", pool=f"pool{p}",
                priority=50, resources=Resources(mem=600.0, cpus=1.0),
                command="true")
            for p in range(2) for i in range(30)
        ])
        pools = [p for p in store.pools.values() if p.schedules_jobs]
        for pool in pools:
            scheduler.rank_cycle(pool)
        if mode == "pipelined":
            outcomes = scheduler.match_cycle_pipelined()
        elif mode == "batched":
            outcomes = scheduler.match_cycle_all_pools()
        else:
            outcomes = {p.name: scheduler.match_cycle(p) for p in pools}
        return sorted((j.uuid, o.hostname)
                      for out in outcomes.values()
                      for j, o in out.matched)

    serial = run("serial")
    assert run("pipelined") == serial
    assert run("batched") == serial


@pytest.mark.parametrize("trace", ["standard", "completion_heavy"])
def test_sim_trace_placements_identical_with_residency(trace):
    """Acceptance bar: the standard and completion-heavy sim traces
    place identically with residency on and off."""
    from cook_tpu.sim.loadgen import completion_heavy_trace
    from cook_tpu.sim.simulator import (SimConfig, Simulator, TraceHost,
                                        TraceJob)

    def standard_trace():
        rng = np.random.default_rng(3)
        jobs = [TraceJob(uuid=f"j{i}", user=f"u{i % 4}",
                         submit_time_ms=int(rng.integers(0, 120_000)),
                         runtime_ms=int(rng.integers(30_000, 120_000)),
                         mem=float(rng.choice([200, 400, 800])),
                         cpus=float(rng.choice([1, 2])))
                for i in range(40)]
        hosts = [TraceHost(node_id=f"n{i}", hostname=f"n{i}", mem=2000,
                           cpus=8) for i in range(8)]
        return jobs, hosts

    def run(resident):
        if trace == "standard":
            jobs, hosts = standard_trace()
        else:
            jobs, hosts = completion_heavy_trace(jobs=24, hosts=4)
        config = SimConfig(
            cycle_ms=30_000, max_cycles=30, resident=resident,
            scheduler=SchedulerConfig(device_telemetry=False),
        )
        result = Simulator(jobs, hosts, config).run()
        return sorted((r["job_uuid"], r["host"], r["start_ms"])
                      for r in result.rows
                      if r.get("start_ms") is not None)

    assert run(True) == run(False)


def test_sim_summary_reports_device_state():
    from cook_tpu.sim.simulator import (SimConfig, Simulator, TraceHost,
                                        TraceJob)

    jobs = [TraceJob(uuid=f"j{i}", user="u", submit_time_ms=0,
                     runtime_ms=60_000, mem=300.0, cpus=1.0)
            for i in range(20)]
    hosts = [TraceHost(node_id=f"n{i}", hostname=f"n{i}", mem=1000,
                       cpus=4) for i in range(4)]
    result = Simulator(jobs, hosts, SimConfig(
        cycle_ms=30_000, max_cycles=20, resident=True,
        scheduler=SchedulerConfig(device_telemetry=False))).run()
    ds = result.data_plane["device_state"]
    assert ds["cycles"] > 0
    assert ds["rebuilds"] >= 1


# ------------------------------------------------------------ speculation


def test_speculation_drops_on_resident_epoch_bump():
    """A resident-state invalidation between speculative dispatch and
    commit vetoes the commit: the speculative problem was built from
    dropped device tensors."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock",
        [MockHost(node_id="h0", hostname="h0", mem=1000, cpus=4,
                  pool="default")],
        clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=0, device_residency=True,
                          quality_audit_every=0),
        speculation=True,
        speculation_horizon_ms=10_000,
        predictor_min_samples=1))
    jobs = [make_job(user="u0", mem=1000, cpus=4).with_(
        uuid=f"j{i}", expected_runtime_ms=10_000) for i in range(3)]
    store.submit_jobs(jobs)

    def cycle():
        pool = store.pools["default"]
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
        return scheduler.recorder.records(limit=1)[0]

    cycle()                                   # j0 fresh; predictor cold
    clock.advance(10_000)
    cluster.advance_to(clock())
    cycle()                                   # j1 fresh; speculates j2
    assert scheduler.speculator.stats_json()["inflight"] == ["default"]
    # the inducing invalidation: resident state dropped mid-flight
    scheduler.device_state.invalidate()
    clock.advance(10_000)
    cluster.advance_to(clock())
    record = cycle()
    assert record.speculation == "dropped"
    assert record.speculation_drop == "epoch-stale"


def test_speculation_hit_with_residency_enabled():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock",
        [MockHost(node_id="h0", hostname="h0", mem=1000, cpus=4,
                  pool="default")],
        clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=0, device_residency=True,
                          quality_audit_every=0),
        speculation=True,
        speculation_horizon_ms=10_000,
        predictor_min_samples=1))
    jobs = [make_job(user="u0", mem=1000, cpus=4).with_(
        uuid=f"j{i}", expected_runtime_ms=10_000) for i in range(3)]
    store.submit_jobs(jobs)

    def cycle():
        pool = store.pools["default"]
        scheduler.rank_cycle(pool)
        outcome = scheduler.match_cycle(pool)
        return outcome, scheduler.recorder.records(limit=1)[0]

    cycle()
    clock.advance(10_000)
    cluster.advance_to(clock())
    cycle()
    clock.advance(10_000)
    cluster.advance_to(clock())
    outcome, record = cycle()
    assert record.speculation == "hit"
    assert [j.uuid for j, _ in outcome.matched] == ["j2"]


# ---------------------------------------------------- resident DRU columns


def test_resident_array_reuses_unchanged_content():
    state = DeviceResidentState()
    a = np.arange(16, dtype=np.float32)
    d1 = state.resident_array("p", "dru.mem", a)
    d2 = state.resident_array("p", "dru.mem", a.copy())
    assert d1 is d2
    d3 = state.resident_array("p", "dru.mem", a + 1)
    assert d3 is not d1
    assert np.allclose(np.asarray(d3), a + 1)


def test_rank_cycle_moves_zero_dru_bytes_when_queue_unchanged():
    store, scheduler = resident_rig(n_jobs=50)
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    scheduler.rank_cycle(pool)  # queue membership unchanged
    totals0 = data_plane.LEDGER.family_totals().get(
        data_plane.FAM_DRU, {}).get("h2d_bytes", 0)
    scheduler.rank_cycle(pool)
    totals1 = data_plane.LEDGER.family_totals().get(
        data_plane.FAM_DRU, {}).get("h2d_bytes", 0)
    assert totals1 == totals0


# ---------------------------------------------------------- debug surface


def test_snapshot_all_reports_mirrors():
    store, scheduler = resident_rig(n_jobs=20)
    run_cycle(store, scheduler)
    # the process-wide snapshot may hold OTHER live schedulers' states
    # (weakref registry); assert on THIS scheduler's entry
    snap = snapshot_all()
    assert snap["enabled"]
    mine = scheduler.device_state.debug_json()
    assert mine in snap["states"]
    assert mine["pools"]["default"]["resident_bytes"] > 0
    assert mine["pools"]["default"]["last"]["rebuild"] is True


def test_quantized_dtype_is_two_bytes():
    assert quantized_dtype().itemsize == 2


# ------------------------------------------------- fused fine-pass scorer


def test_best_node_batched_matches_per_block_best_node():
    from cook_tpu.ops.pallas_match import best_node, best_node_batched

    rng = np.random.default_rng(0)
    b, s, n, r = 3, 16, 32, 4
    d = rng.uniform(1, 10, (b, s, r)).astype(np.float32)
    av = rng.uniform(0, 20, (b, n, r)).astype(np.float32)
    tot = (av[:, :, :2] + 5).astype(np.float32)
    nv = rng.uniform(size=(b, n)) > 0.2
    feas = rng.uniform(size=(b, s, n)) > 0.3
    bv, bi = best_node_batched(jnp.asarray(d), jnp.asarray(av),
                               jnp.asarray(tot), jnp.asarray(nv),
                               jnp.asarray(feas), interpret=True)
    for k in range(b):
        v1, i1 = best_node(jnp.asarray(d[k]), jnp.asarray(av[k]),
                           jnp.asarray(tot[k]), jnp.asarray(nv[k]),
                           jnp.asarray(feas[k]), interpret=True)
        assert np.allclose(np.asarray(bv[k]), np.asarray(v1))
        assert np.array_equal(np.asarray(bi[k]), np.asarray(i1))


def test_hierarchical_fused_fine_backend_parity():
    """The fused fine-pass scorer holds packing parity vs the flat CPU
    greedy (>= 0.95, the hierarchical parity floor) and stamps its
    backend label."""
    from cook_tpu.ops import cpu_reference as ref
    from cook_tpu.ops.hierarchical import HierParams, hierarchical_match
    from cook_tpu.ops.match import MatchProblem

    rng = np.random.default_rng(0)
    j, n = 512, 128
    demands = np.stack([rng.choice([512, 1024, 2048], j),
                        rng.choice([1, 2, 4], j),
                        np.zeros(j)], axis=-1).astype(np.float32)
    totals = np.stack([np.full(n, 65536.0), np.full(n, 32.0)],
                      axis=-1).astype(np.float32)
    avail = np.concatenate(
        [totals * rng.uniform(0.2, 1.0, (n, 1)).astype(np.float32),
         np.zeros((n, 1), np.float32)], axis=-1)
    problem = MatchProblem(
        demands=jnp.asarray(demands), job_valid=jnp.ones(j, bool),
        avail=jnp.asarray(avail), totals=jnp.asarray(totals),
        node_valid=jnp.ones(n, bool), feasible=None)
    result, stats = hierarchical_match(problem, params=HierParams(
        nodes_per_block=32, chunk=128, kc=16, fine_backend="pallas"))
    assert stats["backend"] == "pallas-fine"
    cpu = ref.np_greedy_match(demands, avail, totals)
    q_cpu = ref.packing_quality(demands, cpu)
    q_dev = ref.packing_quality(demands, np.asarray(result.assignment))
    eff = q_dev["cpus_placed"] / q_cpu["cpus_placed"]
    assert eff >= 0.95, eff


def test_hier_fine_backend_validated():
    from cook_tpu.ops.hierarchical import HierParams

    with pytest.raises(ValueError):
        HierParams(fine_backend="nope")
    with pytest.raises(ValueError):
        MatchConfig(hierarchical_fine_backend="nope")
