"""Topology-aware gang scheduling (ops/gang.py + scheduler/gang.py):
the all-or-nothing property across every match path, numpy/device kernel
parity, store submit-batch invariants, drain-vs-kill admission, the
block-aware fragmentation stat, and elastic block-shaped headroom."""
import numpy as np
import pytest

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.elastic import CapacityPlanner, ElasticParams
from cook_tpu.models.entities import (
    ConstraintOperator,
    Group,
    GroupPlacementType,
    HostPlacement,
    InstanceStatus,
    JobConstraint,
    Pool,
    Resources,
)
from cook_tpu.models.store import JobStore, TransactionVetoed
from cook_tpu.obs.fairness import FairnessObservatory
from cook_tpu.ops.gang import (
    block_free_hosts,
    gang_filter,
    np_block_free_hosts,
    np_gang_filter,
    np_gang_repair,
)
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.gang import (
    GangAdmission,
    gang_reservation_tag,
    plan_gang_admissions,
    waiting_gangs,
)
from cook_tpu.scheduler.matcher import MatchConfig
from cook_tpu.scheduler.rebalancer import RebalancerParams

from conftest import FakeClock, make_job

BLOCK_HOSTS = 4


def _hosts(n, mem=1000.0, cpus=8.0):
    """Hosts h0..h{n-1} each advertising a `slot` attribute so tests can
    pin filler jobs deterministically (EQUALS constraint)."""
    return [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=mem, cpus=cpus,
                     attributes=(("slot", f"h{i}"),)) for i in range(n)]


def _pinned(host, mem=800.0, user="filler", **kw):
    return make_job(
        user=user, mem=mem, priority=100,
        constraints=(JobConstraint("slot", ConstraintOperator.EQUALS,
                                   host),),
        **kw)


def _gang_jobs(group, k, mem=500.0, user="ganguser", **kw):
    return [make_job(user=user, mem=mem, gang_size=k, group_uuid=group,
                     **kw) for _ in range(k)]


def _gang_group(group):
    return Group(uuid=group, name=f"gang-{group}",
                 host_placement=HostPlacement(
                     type=GroupPlacementType.UNIQUE))


def _placed_hosts(store, group):
    """Hostnames of live instances across the group's member jobs."""
    out = []
    for uuid in store.groups[group].job_uuids:
        for inst in store.job_instances(uuid):
            if not inst.status.terminal:
                out.append(inst.hostname)
    return out


def _block(hostname):
    return int(hostname[1:]) // BLOCK_HOSTS


# -------------------------------------- the all-or-nothing property test


PATHS = ("serial", "batched", "pipelined", "hierarchical")


def _path_config(path):
    kw = dict(gang_enabled=True, topology_block_hosts=BLOCK_HOSTS)
    if path == "batched":
        kw["chunk"] = 4
    elif path == "hierarchical":
        kw["hierarchical_threshold"] = 1
        kw["hierarchical_nodes_per_block"] = BLOCK_HOSTS
    return SchedulerConfig(match=MatchConfig(**kw))


def _cycle(scheduler, store, path):
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    if path == "pipelined":
        return scheduler.match_cycle_pipelined()["default"]
    return scheduler.match_cycle(pool)


@pytest.mark.parametrize("path", PATHS)
def test_gang_never_partially_places_across_paths(path):
    """THE acceptance property: whichever solve produced the assignment
    (serial / chunked / pipelined / hierarchical), a gang places with
    ALL k members on distinct hosts inside one topology block — or not
    at all.  The rig leaves 3 scattered free hosts (2 in block 0, 1 in
    block 1): a 3-gang must wait while a 2-gang lands whole."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster("m", _hosts(8), clock=clock)
    scheduler = Scheduler(store, [cluster], _path_config(path))

    # fillers pin busy hosts: free = {h0, h2} in block 0, {h7} in block 1
    busy = ("h1", "h3", "h4", "h5", "h6")
    store.submit_jobs([_pinned(h, expected_runtime_ms=60_000)
                       for h in busy])
    outcome = _cycle(scheduler, store, path)
    assert len(outcome.matched) == len(busy)

    store.submit_jobs(_gang_jobs("gang-a", 3), [_gang_group("gang-a")])
    store.submit_jobs(_gang_jobs("gang-b", 2), [_gang_group("gang-b")])
    _cycle(scheduler, store, path)

    # gang-b fits whole in block 0; gang-a has no 3-free block anywhere —
    # a naive solver would scatter it over h0/h2/h7 (partial after the
    # UNIQUE/block strip), so zero placements IS the property
    placed_b = _placed_hosts(store, "gang-b")
    assert sorted(placed_b) == ["h0", "h2"]
    assert len({_block(h) for h in placed_b}) == 1
    assert _placed_hosts(store, "gang-a") == []

    # fillers drain -> block 1 frees whole -> gang-a lands atomically
    clock.advance(70_000)
    cluster.advance_to(clock())
    _cycle(scheduler, store, path)
    placed_a = _placed_hosts(store, "gang-a")
    assert len(placed_a) == 3
    assert len(set(placed_a)) == 3
    assert len({_block(h) for h in placed_a}) == 1


# ------------------------------------------------- numpy/device parity


def test_gang_filter_matches_numpy_twin_fuzz():
    rng = np.random.default_rng(7)
    for _ in range(25):
        J, N, G = 12, 8, 3
        gang_id = rng.integers(-1, G, size=J).astype(np.int32)
        gang_need = np.zeros(J, dtype=np.int32)
        for g in range(G):
            rows = gang_id == g
            if rows.any():
                gang_need[rows] = rng.integers(2, 5)
        assignment = rng.integers(-1, N, size=J).astype(np.int32)
        for npb in (0, 4):
            want_a, want_s = np_gang_filter(assignment, gang_id,
                                            gang_need, npb)
            got_a, got_s = gang_filter(assignment, gang_id, gang_need,
                                       num_gangs=G, num_nodes=N,
                                       nodes_per_block=npb)
            np.testing.assert_array_equal(np.asarray(got_a), want_a)
            np.testing.assert_array_equal(np.asarray(got_s), want_s)


def test_block_free_hosts_matches_numpy_twin():
    rng = np.random.default_rng(11)
    avail = rng.uniform(0, 1000, size=(8, 2)).astype(np.float32)
    node_valid = rng.random(8) > 0.3
    demand = np.array([400.0, 2.0], dtype=np.float32)
    want = np_block_free_hosts(avail, node_valid, demand, 4)
    got = np.asarray(block_free_hosts(avail, node_valid, demand,
                                      nodes_per_block=4))
    np.testing.assert_array_equal(got, want)


def test_np_gang_repair_spreads_stacked_gang():
    """Flat best-fit stacks all members on one host; repair must retry
    the gang whole on distinct hosts of one block."""
    gang_id = np.array([0, 0, 0, -1], dtype=np.int32)
    gang_need = np.array([3, 3, 3, 0], dtype=np.int32)
    assignment = np.array([0, 0, 0, 5], dtype=np.int32)  # stacked
    demands = np.full((4, 2), 100.0)
    avail = np.full((8, 2), 1000.0)
    out = np_gang_repair(assignment, gang_id, gang_need, demands, avail,
                         None, 4)
    hosts = out[:3]
    assert (hosts >= 0).all()
    assert np.unique(hosts).size == 3
    assert np.unique(hosts // 4).size == 1
    assert out[3] == 5  # non-gang rows never move


def test_np_gang_repair_rehomes_block_split_gang():
    gang_id = np.array([0, 0], dtype=np.int32)
    gang_need = np.array([2, 2], dtype=np.int32)
    assignment = np.array([0, 4], dtype=np.int32)  # blocks 0 and 1
    demands = np.full((2, 2), 100.0)
    avail = np.full((8, 2), 1000.0)
    out = np_gang_repair(assignment, gang_id, gang_need, demands, avail,
                         None, 4)
    assert (out >= 0).all()
    assert np.unique(out // 4).size == 1


def test_np_gang_repair_leaves_impossible_gang_unplaced():
    gang_id = np.array([0, 0, 0], dtype=np.int32)
    gang_need = np.array([3, 3, 3], dtype=np.int32)
    assignment = np.array([0, 1, -1], dtype=np.int32)
    demands = np.full((3, 2), 100.0)
    avail = np.zeros((8, 2))
    avail[0] = avail[1] = 1000.0  # only two hosts have capacity
    out = np_gang_repair(assignment, gang_id, gang_need, demands, avail,
                         None, 4)
    assert (out == -1).all()


# ----------------------------------------------- store batch invariants


def _veto(store, jobs, groups=(), match=""):
    with pytest.raises(TransactionVetoed, match=match):
        store.submit_jobs(jobs, groups)


def test_store_gang_submit_invariants(store):
    store.set_pool(Pool(name="other"))
    _veto(store, [make_job(gang_size=1)], match="gang_size 1")
    _veto(store, [make_job(gang_size=2)], match="requires a group")
    g = _gang_group("g-bad")
    _veto(store, [make_job(gang_size=2, group_uuid="g-bad"),
                  make_job(gang_size=3, group_uuid="g-bad")], [g],
          match="disagree")
    _veto(store, [make_job(gang_size=2, group_uuid="g-bad"),
                  make_job(gang_size=2, group_uuid="g-bad",
                           pool="other")], [g], match="span pools")
    _veto(store, [make_job(gang_size=3, group_uuid="g-bad"),
                  make_job(gang_size=3, group_uuid="g-bad")], [g],
          match="submit atomically")
    # a whole gang in one batch lands, and its group cannot be extended
    ok = _gang_jobs("g-ok", 2)
    store.submit_jobs(ok, [_gang_group("g-ok")])
    assert set(store.groups["g-ok"].job_uuids) == {j.uuid for j in ok}
    _veto(store, _gang_jobs("g-ok", 2), match="extended")


# --------------------------------------------- drain-vs-kill admission


class _FixedPredictor:
    def __init__(self, runtime_ms):
        self.runtime_ms = runtime_ms

    def predict_runtime_ms(self, user, command):
        return self.runtime_ms


def _admission_rig(clock, elapsed_ms):
    """One 4-host block: h0/h1 free, h2/h3 each running one task that
    started `elapsed_ms` ago."""
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    running = [make_job(user="occupant", mem=900.0) for _ in range(2)]
    store.submit_jobs(running)
    clock.advance(-elapsed_ms)
    for i, job in enumerate(running):
        store.create_instance(job.uuid, f"t{i}", hostname=f"h{i + 2}",
                              compute_cluster="m")
        store.update_instance_state(f"t{i}", InstanceStatus.RUNNING)
    clock.advance(elapsed_ms)
    gang = _gang_jobs("g-adm", 4, mem=500.0)
    store.submit_jobs(gang, [_gang_group("g-adm")])
    spare = {"h0": Resources(mem=1000, cpus=8),
             "h1": Resources(mem=1000, cpus=8),
             "h2": Resources(mem=100, cpus=8),
             "h3": Resources(mem=100, cpus=8)}
    return store, gang, spare


def _plan(store, gang, spare, predictor, **params):
    return plan_gang_admissions(
        store, store.pools["default"], gang, spare,
        nodes_per_block=4, predictor=predictor,
        params=RebalancerParams(**params), now_ms=store.clock())


def test_admission_prefers_drain_when_predicted_cheap(clock):
    """Preempt-less admission: victims ran 600 s (killing wastes 1200 s)
    and the predictor expects them done in 30 s — the planner reserves
    the block and kills nobody."""
    store, gang, spare = _admission_rig(clock, elapsed_ms=600_000)
    [adm] = _plan(store, gang, spare, _FixedPredictor(630_000.0))
    assert adm.mode == "drain"
    assert adm.victims == []
    assert adm.hosts == ["h0", "h1", "h2", "h3"]
    assert adm.predicted_wait_ms == pytest.approx(30_000.0)


def test_admission_preempts_when_drain_over_budget(clock):
    """Fresh victims (5 s elapsed, nothing to waste) predicted to run
    ~995 s more: drain blows the wait ceiling, so kill wins."""
    store, gang, spare = _admission_rig(clock, elapsed_ms=5_000)
    [adm] = _plan(store, gang, spare, _FixedPredictor(1_000_000.0))
    assert adm.mode == "preempt"
    assert sorted(adm.victims) == ["t0", "t1"]
    assert adm.victim_wasted_s == pytest.approx(10.0)


def test_admission_drain_needs_wasted_work_to_beat(clock):
    """The wasted-factor leg: same 30 s predicted drain, but the victims
    just started — killing wastes ~10 s, under the 30 s wait, so the
    break-even factor tips the decision to preempt."""
    store, gang, spare = _admission_rig(clock, elapsed_ms=5_000)
    [adm] = _plan(store, gang, spare, _FixedPredictor(35_000.0))
    assert adm.mode == "preempt"


def test_waiting_gangs_skips_partial_complements(clock):
    members = _gang_jobs("g-part", 3)[:2]  # two of three present
    assert waiting_gangs(members) == []
    whole = _gang_jobs("g-whole", 2)
    gangs = waiting_gangs(whole + members)
    assert [g for g, _ in gangs] == ["g-whole"]


def test_admissions_capped_per_cycle(clock):
    store, gang, spare = _admission_rig(clock, elapsed_ms=5_000)
    second = _gang_jobs("g-two", 4, mem=500.0)
    store.submit_jobs(second, [_gang_group("g-two")])
    adms = _plan(store, gang + second, spare,
                 _FixedPredictor(1_000_000.0), gang_max_admissions=1)
    assert len(adms) == 1 and adms[0].group_uuid == "g-adm"


# ------------------------------------- scheduler-level admission cycle


def _fleet_rig(**config_kw):
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster("m", _hosts(4), clock=clock)
    scheduler = Scheduler(
        store, [cluster],
        SchedulerConfig(match=MatchConfig(
            gang_enabled=True, topology_block_hosts=BLOCK_HOSTS),
            **config_kw))
    pool = store.pools["default"]
    # occupants fill the whole block (same user as the gang, so the DRU
    # rebalancer stays quiet and only gang admission can act)
    store.submit_jobs([
        _pinned(f"h{i}", mem=900.0, user="ganguser",
                expected_runtime_ms=60_000) for i in range(4)])
    scheduler.rank_cycle(pool)
    assert len(scheduler.match_cycle(pool).matched) == 4
    clock.advance(30_000)
    store.submit_jobs(_gang_jobs("g-core", 4, mem=900.0),
                      [_gang_group("g-core")])
    scheduler.rank_cycle(pool)
    return clock, store, cluster, scheduler, pool


def test_core_admission_preempts_reserves_and_places(clock):
    """No predictor -> drain ETA unknown -> kill path: the cycle kills
    the block's occupants, reserves the hosts gang:<group>, and the next
    match places the gang whole — releasing the reservations."""
    clock, store, cluster, scheduler, pool = _fleet_rig()
    scheduler.rebalance_cycle(pool)
    [adm] = scheduler.last_gang_admissions
    assert adm["mode"] == "preempt"
    tag = gang_reservation_tag("g-core")
    assert set(scheduler.host_reservations.values()) == {tag}
    assert len(scheduler.host_reservations) == 4
    # victims transacted like rebalancer kills (fairness-ledger visible)
    roll = scheduler.fairness.snapshot()["pools"]["default"]["rollups"]
    assert roll["tasks_preempted"] == 4
    assert roll["wasted_s"]["fairness"] == pytest.approx(120.0)

    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    placed = _placed_hosts(store, "g-core")
    assert len(placed) == 4 and len(set(placed)) == 4
    assert len(outcome.matched) == 4
    assert scheduler.host_reservations == {}


def test_core_admission_drains_without_killing(clock):
    """With the runtime predictor warm (occupants predicted done in
    ~30 s, killing would waste 120 s) admission goes preempt-less: hosts
    reserved, nobody dies, and the gang lands after natural drain."""
    clock, store, cluster, scheduler, pool = _fleet_rig(
        backfill_weight=0.01)
    for _ in range(3):
        scheduler.predictor.observe("ganguser", "true", 60_000.0)
    scheduler.rebalance_cycle(pool)
    [adm] = scheduler.last_gang_admissions
    assert adm["mode"] == "drain"
    assert adm["victims"] == []
    assert adm["predicted_wait_ms"] == pytest.approx(30_000.0)
    assert len(scheduler.host_reservations) == 4
    # nobody was preempted: all four occupants still running
    assert len(store.running_instances("default")) == 4

    clock.advance(40_000)
    cluster.advance_to(clock())
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    placed = _placed_hosts(store, "g-core")
    assert len(placed) == 4 and len(set(placed)) == 4
    assert scheduler.host_reservations == {}


def test_core_prunes_stale_gang_reservations(clock):
    clock, store, cluster, scheduler, pool = _fleet_rig()
    scheduler.rebalance_cycle(pool)
    assert len(scheduler.host_reservations) == 4
    # the gang leaves the queue (canceled): its reservations must not
    # squat on the block
    store.kill_jobs(store.groups["g-core"].job_uuids)
    scheduler.rank_cycle(pool)
    scheduler.rebalance_cycle(pool)
    assert scheduler.host_reservations == {}


# ------------------------------------------- block-aware fragmentation


def _frag_entry(i, block):
    return {"t_ms": 1000 + i, "preemptor_job": f"j{i}",
            "preemptor_user": "starved", "hostname": f"h{i}",
            "block": block, "min_preempted_dru": 2.0,
            "victims": [{"task_id": f"t{i}", "user": "hog", "dru": 2.0,
                         "wasted_s": 1.0, "mem": 100.0, "cpus": 1.0,
                         "gpus": 0.0}],
            "freed": {"mem": 100.0, "cpus": 1.0, "gpus": 0.0}}


def test_fragmentation_is_block_aware():
    contiguous = FairnessObservatory()
    contiguous.record_decisions(
        "default", [_frag_entry(i, block=0) for i in range(3)])
    frag = contiguous._fragmentation("default")
    assert frag["contiguous_share"] == 1.0
    assert frag["fragmentation"] == 0.0
    assert frag["blocks"] == 1

    scattered = FairnessObservatory()
    scattered.record_decisions(
        "default", [_frag_entry(i, block=i) for i in range(3)])
    frag = scattered._fragmentation("default")
    # same freed memory, three blocks: no gang can use it whole
    assert frag["contiguous_share"] == pytest.approx(1 / 3, abs=1e-3)
    assert frag["fragmentation"] > 0.6
    assert frag["blocks"] == 3


# --------------------------------------------- elastic block headroom


def test_elastic_block_shortfall_detects_fragmented_spare(store):
    planner = CapacityPlanner(store, [], txn=lambda *a, **k: None,
                              params=ElasticParams(gang_block_hosts=4))
    pending = _gang_jobs("g-el", 3, mem=500.0)
    fit = Resources(mem=1000, cpus=8)
    tight = Resources(mem=100, cpus=8)
    # 4 member-sized hosts fleet-wide, but 2 per block: scalar spare
    # says fine, the gang of 3 says starved
    spare = {"h0": fit, "h1": fit, "h2": tight, "h3": tight,
             "h4": fit, "h5": fit, "h6": tight, "h7": tight}
    short = planner._gang_block_shortfall(pending, spare)
    assert short is not None
    assert short["gang_size"] == 3
    assert short["best_block"] == 2
    assert "mem" in short["dims"]
    # widen one block to 3 free hosts: no shortfall
    spare["h2"] = fit
    assert planner._gang_block_shortfall(pending, spare) is None
