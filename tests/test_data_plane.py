"""Device data-plane observatory (cook_tpu/obs/data_plane.py): transfer
ledger families, the residency ledger's rebuild_fraction (THE inducing
test: cold cycle ~1.0, unchanged-pool re-cycle ~0.0, single-row store
mutation in between), padding-waste accounting, fallback-family
bucketing of the quality audit's device_put, pipelined per-cycle
disjointness, speculation-hit near-zero H2D, roofline attribution, the
`GET /debug/device` endpoint, and the bench-gate byte columns."""
import json

import numpy as np
import pytest

import jax

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import (
    ConstraintOperator,
    Job,
    JobConstraint,
    Pool,
    Resources,
)
from cook_tpu.models.store import JobStore
from cook_tpu.obs import data_plane
from cook_tpu.obs.compile_observatory import CompileObservatory
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.matcher import MatchConfig
from tests.conftest import FakeClock, make_job

NOWHERE = (JobConstraint("rack", ConstraintOperator.EQUALS, "nowhere"),)


def blocked_job(uuid, user="u", mem=200.0):
    """A job no host can satisfy (EQUALS constraint on an attribute no
    host carries): it stays WAITING across cycles — the steady-queue
    shape the residency ledger measures — while still encoding real
    feasibility rows."""
    return Job(uuid=uuid, user=user, pool="default", command="t",
               resources=Resources(mem=mem, cpus=1),
               constraints=NOWHERE)


def make_scheduler(n_hosts=2, clock=None, **config_kw):
    store = JobStore(clock=clock) if clock is not None else JobStore()
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock",
        [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4000, cpus=8,
                  pool="default") for i in range(n_hosts)],
        clock=store.clock)
    scheduler = Scheduler(store, [cluster],
                          SchedulerConfig(match=MatchConfig(chunk=0),
                                          **config_kw))
    return store, cluster, scheduler


def run_cycle(scheduler, store):
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    return scheduler.recorder.records(limit=1)[-1]


# ------------------------------------------------------- scope mechanics


def test_scope_attribution_and_family_labels():
    scope = data_plane.CycleDataPlane("p", 1)
    with data_plane.activate(scope):
        data_plane.note_h2d(100, family=data_plane.FAM_NODE_ENCODE)
        with data_plane.family(data_plane.FAM_DRU):
            data_plane.note_d2h(40)          # labeled by ambient family
        data_plane.note_d2h(7)               # no family -> "other"
        data_plane.note_residency(30, 70)
        data_plane.note_padding("match", (8, 8), 10, 64)
    assert scope.h2d_bytes == 100 and scope.d2h_bytes == 47
    fams = scope.families_json()
    assert fams[data_plane.FAM_DRU]["d2h_bytes"] == 40
    assert fams[data_plane.FAM_OTHER]["d2h_bytes"] == 7
    assert scope.rebuild_fraction == pytest.approx(0.3)
    assert scope.padding_waste == pytest.approx(1 - 10 / 64)
    # zero-byte notes are dropped, not minted as empty family slots
    data_plane.note_h2d(0, family="never")
    assert "never" not in data_plane.LEDGER.family_totals()


def test_activate_is_reentrant_and_none_tolerant():
    scope = data_plane.CycleDataPlane("p", 1)
    with data_plane.activate(None):
        assert data_plane.active_scope() is None
    with data_plane.activate(scope), data_plane.activate(scope):
        data_plane.note_h2d(5, family="x")
    # credited ONCE (innermost wins; same object either way)
    assert scope.h2d_bytes == 5


def test_empty_scope_not_folded_into_cycle_ring():
    before = len(data_plane.LEDGER.snapshot()["cycles"])
    data_plane.LEDGER.finish_cycle(data_plane.CycleDataPlane("idle", 9))
    assert len(data_plane.LEDGER.snapshot()["cycles"]) == before


def test_detached_masks_the_enclosing_scope():
    """Audit/shadow sections inside an activated cycle report to the
    ledger only — never to the driving cycle's record."""
    scope = data_plane.CycleDataPlane("p", 1)
    fam = data_plane.FAM_FALLBACK

    def fallback_d2h():
        slot = data_plane.LEDGER.family_totals().get(fam, {})
        return slot.get("d2h_bytes", 0)

    before = fallback_d2h()
    with data_plane.activate(scope):
        with data_plane.detached(), data_plane.family(fam):
            data_plane.note_d2h(512)
    assert scope.d2h_bytes == 0
    assert fallback_d2h() == before + 512


def test_snapshot_cycles_zero_returns_no_cycles():
    scope = data_plane.CycleDataPlane("p", 3)
    scope.note_h2d(1, "x")
    data_plane.LEDGER.finish_cycle(scope)
    assert data_plane.LEDGER.snapshot(cycles=0)["cycles"] == []
    assert data_plane.LEDGER.snapshot(cycles=1)["cycles"]


def test_quality_shadow_solve_stays_off_the_cycle_record():
    """Every-cycle shadow sampling must not inflate the record's D2H:
    its full-problem fetches bucket under `fallback` in the ledger and
    bypass the active cycle scope."""
    store, _cluster, scheduler = make_scheduler(quality_sample_every=1)
    store.submit_jobs([blocked_job("j0")])
    record = run_cycle(scheduler, store)
    # only the assignment fetch lands on the record (shadow fetched the
    # whole padded problem — orders of magnitude more than this)
    assert record.d2h_bytes < 1024
    assert data_plane.FAM_FALLBACK not in record.data_plane
    slot = data_plane.LEDGER.family_totals()[data_plane.FAM_FALLBACK]
    # the shadow fetched the padded demand/avail/totals tensors — far
    # more than the record's own (assignment-only) D2H
    assert slot["d2h_bytes"] > max(record.d2h_bytes, 1024)


# --------------------------------------------- residency (inducing test)


def test_rebuild_fraction_cold_warm_and_single_row_mutation():
    """THE headline signal: a cold cycle rebuilds everything (~1.0), an
    unchanged pool re-served from the encode cache rebuilds nothing
    (~0.0) — yet still re-transfers the full encode tensors, the waste
    item 2(a) removes — and one store mutation (a new job = one fresh
    row) lands strictly in between."""
    store, _cluster, scheduler = make_scheduler()
    store.submit_jobs([blocked_job(f"j{i}") for i in range(10)])
    r1 = run_cycle(scheduler, store)
    assert r1.rebuild_fraction == pytest.approx(1.0)
    assert r1.h2d_bytes > 0

    r2 = run_cycle(scheduler, store)
    assert r2.rebuild_fraction == pytest.approx(0.0)
    # the unchanged pool still re-transferred the full encode tensors:
    # that H2D times (1 - rebuild_fraction) is the device-residency waste
    assert r2.h2d_bytes == r1.h2d_bytes

    store.submit_jobs([blocked_job("fresh")])
    r3 = run_cycle(scheduler, store)
    assert r3.rebuild_fraction == pytest.approx(1 / 11)
    assert 0.0 < r3.rebuild_fraction < 0.5

    # the per-pool residency surface mirrors the last cycle
    res = data_plane.LEDGER.snapshot()["residency"]["default"]
    assert res["rebuild_fraction"] == pytest.approx(r3.rebuild_fraction)
    # and the record's JSON render carries every data-plane field
    body = r3.to_json()
    for key in ("h2d_bytes", "d2h_bytes", "rebuild_fraction",
                "padding_waste", "data_plane"):
        assert key in body
    assert body["data_plane"][data_plane.FAM_FEASIBILITY]["h2d_bytes"] > 0


def test_cache_bypass_reports_full_rebuild_every_cycle():
    store, _cluster, scheduler = make_scheduler(use_encode_cache=False)
    store.submit_jobs([blocked_job(f"j{i}") for i in range(4)])
    run_cycle(scheduler, store)
    r2 = run_cycle(scheduler, store)
    assert r2.rebuild_fraction == pytest.approx(1.0)


def test_padding_waste_on_record_matches_bucket_math():
    store, _cluster, scheduler = make_scheduler(n_hosts=2)
    store.submit_jobs([blocked_job(f"j{i}") for i in range(10)])
    record = run_cycle(scheduler, store)
    # 10 jobs x 2 nodes valid inside the 64 x 64 minimum buckets
    assert record.padding_waste == pytest.approx(1 - 20 / 4096)


# ------------------------------------------------- pipelined disjointness


def test_pipelined_cycles_report_disjoint_byte_counts():
    """Overlapping pool k/k+1 solves attribute bytes to THEIR OWN cycle
    records: per-pool sums equal the ledger's family deltas exactly (no
    double count), and the bigger pool's padded bucket shows up only on
    its own record."""
    store = JobStore()
    store.set_pool(Pool(name="a"))
    store.set_pool(Pool(name="b"))
    hosts_a = [MockHost(node_id="a0", hostname="a0", mem=4000, cpus=8,
                        pool="a")]
    # pool b pads its node axis to 128 (> the 64 minimum bucket), so its
    # per-cycle bytes are strictly larger than pool a's — shared/global
    # accounting could never reproduce that split
    hosts_b = [MockHost(node_id=f"b{i}", hostname=f"b{i}", mem=4000,
                        cpus=8, pool="b") for i in range(70)]
    cluster = MockCluster("mock", hosts_a + hosts_b, clock=store.clock)
    scheduler = Scheduler(store, [cluster],
                          SchedulerConfig(match=MatchConfig(chunk=0)))
    store.submit_jobs(
        [blocked_job(f"a{i}").with_(pool="a") for i in range(3)]
        + [blocked_job(f"b{i}").with_(pool="b") for i in range(3)])
    for name in ("a", "b"):
        scheduler.rank_cycle(store.pools[name])

    families = (data_plane.FAM_NODE_ENCODE, data_plane.FAM_FEASIBILITY,
                data_plane.FAM_SOLVE)
    before = {f: dict(data_plane.LEDGER.family_totals().get(
        f, {"h2d_bytes": 0, "d2h_bytes": 0})) for f in families}
    scheduler.match_cycle_pipelined()
    after = data_plane.LEDGER.family_totals()

    records = {r.pool: r for r in scheduler.recorder.records(limit=2)}
    ra, rb = records["a"], records["b"]
    assert ra.pipelined and rb.pipelined
    assert ra.h2d_bytes > 0 and rb.h2d_bytes > 0
    assert rb.h2d_bytes > ra.h2d_bytes  # 128-node bucket vs 64
    for fam in families:
        delta_h2d = after[fam]["h2d_bytes"] - before[fam]["h2d_bytes"]
        delta_d2h = after[fam]["d2h_bytes"] - before[fam]["d2h_bytes"]
        fa = ra.data_plane.get(fam, {})
        fb = rb.data_plane.get(fam, {})
        assert fa.get("h2d_bytes", 0) + fb.get("h2d_bytes", 0) \
            == delta_h2d, fam
        assert fa.get("d2h_bytes", 0) + fb.get("d2h_bytes", 0) \
            == delta_d2h, fam


# -------------------------------------------------- speculation-hit H2D


def test_speculation_hit_reports_near_zero_h2d():
    """A cycle served from a committed speculation moved its tensors
    during the PREVIOUS cycle's drain: the hit cycle's own record shows
    zero H2D and only the tiny assignment fetch as D2H — the
    device-residency behavior item 2(a) generalizes."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock", [MockHost(node_id="h0", hostname="h0", mem=1000, cpus=4,
                          pool="default")], clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=0), speculation=True,
        speculation_horizon_ms=10_000, predictor_min_samples=1))
    store.submit_jobs([
        make_job(user="u0", mem=1000, cpus=4).with_(
            uuid=f"j{i}", expected_runtime_ms=10_000) for i in range(3)])

    def cycle():
        pool = store.pools["default"]
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
        return scheduler.recorder.records(limit=1)[-1]

    r1 = cycle()                      # j0 fresh
    assert r1.h2d_bytes > 0
    clock.advance(10_000); cluster.advance_to(clock())
    cycle()                           # j1 fresh; speculates j2
    clock.advance(10_000); cluster.advance_to(clock())
    r3 = cycle()                      # served from speculation
    assert r3.speculation == "hit"
    assert r3.h2d_bytes == 0
    assert 0 < r3.d2h_bytes < 4096
    assert r3.data_plane.get(data_plane.FAM_SOLVE, {}).get("d2h_bytes",
                                                           0) > 0


# -------------------------------------------------- fallback bucketing


def test_quality_audit_device_put_buckets_under_fallback_family():
    """The audit re-stages the whole problem host-side (scheduler/
    matcher.audit_match_quality): those bytes land in the distinct
    `fallback` family — device-family totals must not move."""
    from cook_tpu.scheduler.matcher import (
        PoolMatchState,
        audit_match_quality,
        prepare_pool_problem,
    )
    from cook_tpu.scheduler.flight_recorder import NULL_CYCLE

    store, _cluster, scheduler = make_scheduler()
    store.submit_jobs([Job(uuid="j0", user="u", pool="default",
                           command="t",
                           resources=Resources(mem=200, cpus=1))])
    pool = store.pools["default"]
    queue = scheduler.rank_cycle(pool)
    config = MatchConfig(chunk=0)
    prepared = prepare_pool_problem(
        store, pool, queue, scheduler.clusters, config,
        PoolMatchState(num_considerable=100), flight=NULL_CYCLE)
    assert prepared.solvable

    totals_before = data_plane.LEDGER.family_totals()

    def fam_bytes(totals, fam):
        slot = totals.get(fam, {})
        return (slot.get("h2d_bytes", 0), slot.get("d2h_bytes", 0))

    audit_match_quality(prepared, np.zeros(1, dtype=np.int32), "default")
    totals_after = data_plane.LEDGER.family_totals()
    fb_before = fam_bytes(totals_before, data_plane.FAM_FALLBACK)
    fb_after = fam_bytes(totals_after, data_plane.FAM_FALLBACK)
    assert fb_after[0] > fb_before[0]   # the problem's put
    assert fb_after[1] > fb_before[1]   # the exact assignment's fetch
    for fam in (data_plane.FAM_NODE_ENCODE, data_plane.FAM_FEASIBILITY):
        assert fam_bytes(totals_after, fam) == \
            fam_bytes(totals_before, fam), fam


def test_cpu_fallback_cycle_moves_no_device_bytes():
    """Reaction-(c) cycles solve on the host reference: their records
    carry the tensor-build H2D (the problem was still encoded) but no
    solve-fetch D2H, and nothing lands in the device solve family."""
    from cook_tpu import faults

    store, _cluster, scheduler = make_scheduler(
        )
    scheduler.config.match.device_fallback_cycles = 4
    store.submit_jobs([blocked_job("j0")])
    with faults.injected({"point": faults.DEVICE_SOLVE, "times": 1}):
        r1 = run_cycle(scheduler, store)
    assert r1.backend == "cpu-fallback"
    assert r1.data_plane.get(data_plane.FAM_SOLVE,
                             {}).get("d2h_bytes", 0) == 0


# ------------------------------------------------------------- roofline


def test_roofline_probe_inline_caches_cost_in_observatory():
    obs = CompileObservatory()

    @jax.jit
    def f(x):
        return (x @ x).sum()

    x = np.ones((32, 32), dtype=np.float32)
    cost = data_plane.probe_roofline(obs, "toy", (32, 32), "xla", f, x,
                                     inline=True)
    assert cost is not None and cost["flops"] > 0
    assert obs.cost("toy", "32x32", "xla") == cost
    # second probe is a no-op (cost cached)
    assert data_plane.probe_roofline(obs, "toy", (32, 32), "xla", f,
                                     x, inline=True) is None
    # a warm solve wall joins into achieved throughput
    obs.observe_solve("toy", (32, 32), "xla", seconds=0.5)  # compile
    obs.observe_solve("toy", (32, 32), "xla", seconds=0.5)  # warm
    rows = obs.cost_stats()
    assert rows and rows[0]["op"] == "toy"
    assert rows[0]["achieved_gflops"] == pytest.approx(
        cost["flops"] / 0.5 / 1e9)
    assert rows[0]["arithmetic_intensity"] > 0


def test_match_cycle_populates_roofline_cache():
    store, _cluster, scheduler = make_scheduler()
    store.submit_jobs([blocked_job("j0")])
    run_cycle(scheduler, store)
    # the background probe is single-flight; join it via the lock
    with data_plane._probe_lock:
        pass
    rows = scheduler.telemetry.observatory.cost_stats()
    assert any(r["op"] == "match" for r in rows)


def test_cost_analysis_never_raises_on_unlowerable_fn():
    assert data_plane.cost_analysis(lambda x: x, 1) is None


# ------------------------------------------------------- REST endpoint


def test_debug_device_endpoint():
    from cook_tpu.rest.api import ApiConfig, CookApi
    from cook_tpu.rest.server import ServerThread
    import urllib.request

    store, _cluster, scheduler = make_scheduler()
    store.submit_jobs([blocked_job("j0")])
    run_cycle(scheduler, store)
    api = CookApi(store, scheduler, ApiConfig())
    server = ServerThread(api).start()
    try:
        req = urllib.request.Request(
            server.url + "/debug/device",
            headers={"X-Cook-Requesting-User": "admin"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
            body = json.loads(r.read())
    finally:
        server.stop()
    assert body["device_telemetry"] is True
    assert body["transfers"]["h2d_bytes"] > 0
    assert set(body) >= {"transfers", "residency", "padding", "cycles",
                         "roofline"}
    assert data_plane.FAM_NODE_ENCODE in body["transfers"]["families"]
    assert "default" in body["residency"]


# -------------------------------------------------- bench gate / history


def _record(path, backend, phases):
    return {"path": path, "mode": "smoke", "platform": backend,
            "backend": backend, "phases": phases}


def _gate():
    import importlib.util
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))
    import bench_gate

    return bench_gate


def test_bench_gate_diffs_byte_columns_same_backend():
    bench_gate = _gate()
    old = _record("r1", "cpu", {"match": {"p50_ms": 10.0,
                                          "h2d_bytes": 100,
                                          "d2h_bytes": 50}})
    new = _record("r2", "cpu", {"match": {"p50_ms": 10.5,
                                          "h2d_bytes": 100,
                                          "d2h_bytes": 50}})
    code, messages = bench_gate.gate([old, new], 0.2)
    assert code == 0
    assert any("h2d_bytes 100 -> 100" in m for m in messages)


def test_bench_gate_bytes_threshold_fails_on_growth():
    bench_gate = _gate()
    old = _record("r1", "cpu", {"match": {"p50_ms": 10.0,
                                          "h2d_bytes": 100}})
    new = _record("r2", "cpu", {"match": {"p50_ms": 10.0,
                                          "h2d_bytes": 300}})
    code, messages = bench_gate.gate([old, new], 0.2,
                                     bytes_threshold=0.5)
    assert code == 1
    assert any("h2d_bytes 100 -> 300" in m and "REGRESSION" in m
               for m in messages)
    # without the threshold the growth is informational only
    code, _ = bench_gate.gate([old, new], 0.2)
    assert code == 0


def test_bench_gate_match_resident_bytes_gated_by_default():
    """match_resident* phases byte-gate at the TIMING threshold even
    with no --bytes-threshold: warm-cycle byte growth is the regression
    the residency tier exists to catch, never informational."""
    bench_gate = _gate()
    old = _record("r1", "cpu", {
        "match_resident": {"p50_ms": 10.0, "h2d_bytes": 1000},
        "match": {"p50_ms": 10.0, "h2d_bytes": 1000}})
    new = _record("r2", "cpu", {
        "match_resident": {"p50_ms": 10.0, "h2d_bytes": 5000},
        "match": {"p50_ms": 10.0, "h2d_bytes": 5000}})
    code, messages = bench_gate.gate([old, new], 0.2)
    assert code == 1
    assert any("match_resident: h2d_bytes 1000 -> 5000" in m
               and "REGRESSION" in m for m in messages)
    # the ordinary phase's identical growth stays informational
    assert not any("  match: h2d_bytes" in m and "REGRESSION" in m
                   for m in messages)
    # unchanged warm bytes pass
    code, _ = bench_gate.gate([old, old | {"path": "r3"}], 0.2)
    assert code == 0


def test_bench_history_renders_vs_cold_split(tmp_path):
    """The residency warm/cold split: bench_history shows warm-cycle
    H2D as a fraction of the cold rebuild's."""
    import json as _json

    import bench_history

    record = {
        "schema": "cook-bench/v1", "mode": "smoke", "platform": "cpu",
        "backend": "cpu",
        "phases": {
            "match_resident": {"p50_ms": 10.0, "h2d_bytes": 300,
                               "warm_cycles": 3},
            "match_resident_cold": {"p50_ms": 50.0, "h2d_bytes": 1000},
        },
    }
    path = tmp_path / "BENCH_r01_phases.json"
    path.write_text(_json.dumps(record))
    rows = bench_history.history_rows(
        bench_history.collect_records([str(path)]))
    by_phase = {r["phase"]: r for r in rows}
    assert by_phase["match_resident"]["vs_cold"] == "10.0%"
    assert by_phase["match_resident_cold"]["vs_cold"] == "-"


def test_bench_gate_zero_baseline_growth_trips_threshold():
    """Growth from a zero baseline is unbounded, not 0%: a phase that
    moved no bytes suddenly moving megabytes must trip any threshold."""
    bench_gate = _gate()
    old = _record("r1", "cpu", {"match": {"p50_ms": 10.0,
                                          "d2h_bytes": 0}})
    new = _record("r2", "cpu", {"match": {"p50_ms": 10.0,
                                          "d2h_bytes": 52428800}})
    code, messages = bench_gate.gate([old, new], 0.2,
                                     bytes_threshold=0.1)
    assert code == 1
    assert any("from zero" in m and "REGRESSION" in m for m in messages)


def test_bench_gate_bytes_only_cli_inherits_threshold(tmp_path):
    """--bytes-only without --bytes-threshold must still be a GATE:
    main() inherits --threshold so arbitrary byte growth fails."""
    bench_gate = _gate()
    base = {"schema": "cook-bench/v1", "mode": "smoke",
            "platform": "cpu", "backend": "cpu"}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        dict(base, phases={"match": {"p50_ms": 10.0,
                                     "h2d_bytes": 100}})))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        dict(base, phases={"match": {"p50_ms": 10.0,
                                     "h2d_bytes": 1000}})))
    assert bench_gate.main(["--dir", str(tmp_path), "--bytes-only"]) == 1
    # generous explicit threshold passes the same pair
    assert bench_gate.main(["--dir", str(tmp_path), "--bytes-only",
                            "--bytes-threshold", "20.0"]) == 0


def test_bench_gate_bytes_only_fails_on_dropped_measurements():
    """--bytes-only IS the whole gate: a byte column or phase that
    silently vanished from the new record must fail it, exactly like
    the timing gate's missing-phase rule."""
    bench_gate = _gate()
    old = _record("r1", "cpu", {
        "match": {"p50_ms": 10.0, "h2d_bytes": 100},
        "match_xl": {"p50_ms": 5.0, "h2d_bytes": 7}})
    new = _record("r2", "cpu", {"match": {"p50_ms": 10.0}})
    code, messages = bench_gate.gate([old, new], 0.2, bytes_only=True)
    assert code == 1
    assert any("h2d_bytes dropped" in m for m in messages)
    assert any("match_xl: missing" in m for m in messages)


def test_bench_gate_bytes_survive_cross_backend_refusal():
    """Bytes are backend-stable: the byte diff renders even for a pair
    whose timings the gate refuses, and --bytes-only gates such a pair
    cleanly on traffic alone."""
    bench_gate = _gate()
    old = _record("r1", "cpu", {"match": {"p50_ms": 800.0,
                                          "h2d_bytes": 100,
                                          "d2h_bytes": 50}})
    new = dict(_record("r2", "tpu", {"match": {"p50_ms": 5.0,
                                               "h2d_bytes": 100,
                                               "d2h_bytes": 50}}),
               platform="cpu")  # same (mode, platform) family
    code, messages = bench_gate.gate([old, new], 0.2)
    assert code == 1  # timing refusal stands
    assert any("REFUSED" in m for m in messages)
    assert any("h2d_bytes 100 -> 100" in m for m in messages)
    code, messages = bench_gate.gate([old, new], 0.2, bytes_only=True)
    assert code == 0
    assert not any("REFUSED" in m for m in messages)


def test_bench_history_table(tmp_path):
    import bench_history

    record = {"schema": "cook-bench/v1", "mode": "smoke",
              "platform": "cpu", "backend": "cpu",
              "phases": {"match": {"p50_ms": 12.5, "h2d_bytes": 640,
                                   "d2h_bytes": 64},
                         "dru": {"p50_ms": 3.0}}}
    path = tmp_path / "BENCH_r01.json"
    path.write_text(json.dumps(record))
    bench_gate = _gate()
    rows = bench_history.history_rows(
        bench_gate.collect_records([str(path)]))
    assert {r["phase"] for r in rows} == {"match", "dru"}
    match = next(r for r in rows if r["phase"] == "match")
    assert match["h2d_bytes"] == "640" and match["backend"] == "cpu"
    dru = next(r for r in rows if r["phase"] == "dru")
    assert dru["h2d_bytes"] == "-"  # records without the stamp render -
    table = bench_history.render_table(rows)
    assert "BENCH_r01.json" in table and "640" in table
    md = bench_history.render_table(rows, markdown=True)
    assert md.startswith("| round |")
