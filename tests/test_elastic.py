"""The elastic capacity plane (cook_tpu/elastic/ + ops/elastic.py).

Covers the ISSUE-4 acceptance bars:

  * kernel parity against the CPU reference (weighted demand + the
    loan/reclaim plan) and the plan's invariants (reclaim-first,
    headroom, no loan chains);
  * durable ledger: pool/capacity-delta commits are idempotent,
    snapshot+journal replay reconstructs the ledger exactly, and a
    promoted leader reconciles cluster capacity from it;
  * reclaim-before-preemption: a lender pool regaining demand gets its
    capacity back via reclaim BEFORE any in-pool preemption victim is
    chosen — verified across a leader failover mid-flow;
  * simulator A/B: the imbalanced-pool scenario shows lower p50
    queued-job wait with the planner on vs static pools;
  * bucket padding: varying pool/job counts never drive the
    CompileObservatory into an elastic_plan recompile storm;
  * observability: /debug/elastic serves the ring + ledger, and cycle
    records carry the per-pool capacity snapshot + plan linkage.
"""
import json
import threading
import types

import numpy as np
import pytest
import requests

import jax.numpy as jnp

from cook_tpu.cluster.k8s import FakeKubeApi, KubeCluster, KubeNode
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.elastic import CapacityPlanner, ElasticParams
from cook_tpu.models import persistence
from cook_tpu.models.entities import InstanceStatus, Pool, Resources, Share
from cook_tpu.models.store import JobStore, TransactionVetoed
from cook_tpu.ops import cpu_reference as ref
from cook_tpu.ops.common import fetch_result
from cook_tpu.ops.elastic import (
    ElasticProblem,
    solve_capacity_plan,
    weighted_demand,
)
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.rebalancer import RebalancerParams, rebalance_pool
from cook_tpu.txn import TransactionLog
from tests.conftest import FakeClock, make_job


# ------------------------------------------------------------ kernel parity


def _rand_problem(p=8, live=5, seed=0):
    rng = np.random.default_rng(seed)
    demand = rng.uniform(0, 100_000, (p, 3)).astype(np.float32)
    supply = rng.uniform(0, 100_000, (p, 3)).astype(np.float32)
    outstanding = np.zeros((p, p, 3), np.float32)
    outstanding[0, 1] = (5000.0, 8.0, 0.0)
    outstanding[2, 3] = (100.0, 1.0, 0.0)
    pool_valid = np.arange(p) < live
    return demand, supply, outstanding, pool_valid


def test_weighted_demand_matches_cpu_reference():
    rng = np.random.default_rng(1)
    res = rng.uniform(0, 4000, (6, 32, 3)).astype(np.float32)
    valid = rng.uniform(size=(6, 32)) < 0.5
    got = fetch_result(weighted_demand(jnp.asarray(res), jnp.asarray(valid),
                                       jnp.float32(16)))
    want = ref.ref_weighted_demand(res, valid, 16)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-2)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_capacity_plan_matches_cpu_reference(seed):
    demand, supply, outstanding, pool_valid = _rand_problem(seed=seed)
    plan = fetch_result(solve_capacity_plan(
        ElasticProblem(jnp.asarray(demand), jnp.asarray(supply),
                       jnp.asarray(outstanding), jnp.asarray(pool_valid)),
        jnp.float32(0.1)))
    r_ref, l_ref, u_ref = ref.ref_capacity_plan(
        demand, supply, outstanding, pool_valid, 0.1)
    np.testing.assert_allclose(plan.reclaim, r_ref, rtol=1e-4, atol=1e-2)
    np.testing.assert_allclose(plan.loan, l_ref, rtol=1e-4, atol=1e-2)
    np.testing.assert_allclose(plan.shortage, u_ref, rtol=1e-4, atol=1e-1)


def test_plan_invariants_reclaim_first_and_headroom():
    p = 8
    demand = np.zeros((p, 3), np.float32)
    supply = np.zeros((p, 3), np.float32)
    outstanding = np.zeros((p, p, 3), np.float32)
    # pool 0 loaned 40 cpus to pool 1 and now needs 30; pool 1 has 50 free
    demand[0] = (0, 30, 0)
    supply[1] = (0, 50, 0)
    # pool 2 idles with surplus, pool 3 is short — a fresh loan case
    supply[2] = (0, 100, 0)
    demand[3] = (0, 20, 0)
    outstanding[0, 1] = (0, 40, 0)
    plan = fetch_result(solve_capacity_plan(
        ElasticProblem(jnp.asarray(demand), jnp.asarray(supply),
                       jnp.asarray(outstanding),
                       jnp.asarray(np.ones(p, bool))),
        jnp.float32(0.1)))
    # reclaim covers pool 0's shortage from its own outstanding loan —
    # no new loan is minted for it
    assert plan.reclaim[0, 1, 1] == pytest.approx(30.0, abs=1e-3)
    assert plan.loan[:, 0, 1].sum() == pytest.approx(0.0, abs=1e-3)
    # pool 3's shortage is loaned from pool 2's surplus, headroom kept
    assert plan.loan[2, 3, 1] == pytest.approx(20.0, abs=1e-3)
    assert plan.loan[2].sum() <= supply[2, 1] * 0.9 + 1e-3
    # pool 1 still holds borrowed capacity: it must not re-loan it
    assert plan.loan[1].sum() == pytest.approx(0.0, abs=1e-3)


def test_plan_ignores_padded_pools():
    demand, supply, outstanding, pool_valid = _rand_problem(live=3)
    plan = fetch_result(solve_capacity_plan(
        ElasticProblem(jnp.asarray(demand), jnp.asarray(supply),
                       jnp.asarray(outstanding), jnp.asarray(pool_valid)),
        jnp.float32(0.0)))
    assert plan.loan[3:].sum() == 0.0 and plan.loan[:, 3:].sum() == 0.0
    assert plan.reclaim[3:].sum() == 0.0


# ------------------------------------------------------- ledger + txn + io


def _ledger_store(clock=None):
    store = JobStore(clock=clock or FakeClock())
    store.set_pool(Pool(name="lender"))
    store.set_pool(Pool(name="borrower"))
    return store


def test_ledger_apply_clamp_and_net():
    store = _ledger_store()
    txn = TransactionLog(store)
    txn.commit("pool/capacity-delta", {"moves": [
        {"kind": "loan", "from": "lender", "to": "borrower",
         "mem": 1000.0, "cpus": 8.0, "gpus": 0.0}]})
    assert store.net_capacity_adjustment("borrower")["cpus"] == 8.0
    assert store.net_capacity_adjustment("lender")["cpus"] == -8.0
    # reclaim clamps at outstanding: asking 100 back returns only 8
    txn.commit("pool/capacity-delta", {"moves": [
        {"kind": "reclaim", "from": "lender", "to": "borrower",
         "mem": 9999.0, "cpus": 100.0, "gpus": 0.0}]})
    assert store.capacity_ledger == {}
    assert store.net_capacity_adjustment("lender")["cpus"] == 0.0


def test_capacity_delta_validation_and_idempotency():
    store = _ledger_store()
    txn = TransactionLog(store)
    with pytest.raises(TransactionVetoed):
        txn.commit("pool/capacity-delta", {"moves": [
            {"kind": "loan", "from": "lender", "to": "nope", "cpus": 1.0}]})
    with pytest.raises(TransactionVetoed):
        txn.commit("pool/capacity-delta", {"moves": [
            {"kind": "loan", "from": "lender", "to": "lender", "cpus": 1.0}]})
    with pytest.raises(TransactionVetoed):
        txn.commit("pool/capacity-delta", {"moves": [
            {"kind": "loan", "from": "lender", "to": "borrower",
             "cpus": -1.0}]})
    out1 = txn.commit("pool/capacity-delta", {"moves": [
        {"kind": "loan", "from": "lender", "to": "borrower", "cpus": 4.0}]},
        txn_id="cap-1")
    out2 = txn.commit("pool/capacity-delta", {"moves": [
        {"kind": "loan", "from": "lender", "to": "borrower", "cpus": 4.0}]},
        txn_id="cap-1")
    assert out2.duplicate and out2.result == out1.result
    # the duplicate must NOT have double-applied
    assert store.net_capacity_adjustment("borrower")["cpus"] == 4.0


def test_ledger_survives_snapshot_and_journal_replay(tmp_path):
    store = _ledger_store()
    journal = persistence.attach_journal(store,
                                         str(tmp_path / "journal.jsonl"))
    txn = TransactionLog(store, journal=journal)
    txn.commit("pool/capacity-delta", {"moves": [
        {"kind": "loan", "from": "lender", "to": "borrower",
         "mem": 2000.0, "cpus": 16.0, "gpus": 1.0}]})
    txn.commit("pool/capacity-delta", {"moves": [
        {"kind": "reclaim", "from": "lender", "to": "borrower",
         "mem": 500.0, "cpus": 4.0, "gpus": 0.0}]})
    journal.close()
    # journal-only recovery
    recovered = persistence.recover(str(tmp_path))
    assert recovered.capacity_ledger == store.capacity_ledger
    assert recovered.capacity_ledger[("lender", "borrower")]["cpus"] == 12.0
    # snapshot round-trip
    persistence.snapshot(store, str(tmp_path / "snapshot.json"))
    recovered2 = persistence.recover(str(tmp_path))
    assert recovered2.capacity_ledger == store.capacity_ledger
    # a replayed duplicate commit on the recovered store dedupes from
    # the rebuilt transaction table
    txn_ids = list(recovered.txn_results)
    txn2 = TransactionLog(recovered)
    replay = txn2.commit("pool/capacity-delta", {"moves": []},
                         txn_id=txn_ids[0])
    assert replay.duplicate


# ----------------------------------------------------------- cluster scale


def test_mock_scale_materializes_and_withholds_capacity():
    clock = FakeClock()
    cluster = MockCluster("m", [
        MockHost(node_id="l0", hostname="l0", mem=16000, cpus=16,
                 pool="lender"),
        MockHost(node_id="b0", hostname="b0", mem=4000, cpus=4,
                 pool="borrower"),
    ], clock=clock)
    cluster.scale("borrower", {"mem": 8000.0, "cpus": 8.0, "gpus": 0.0})
    cluster.scale("lender", {"mem": -8000.0, "cpus": -8.0, "gpus": 0.0})
    borrower = {o.node_id: o for o in cluster.pending_offers("borrower")}
    assert borrower["elastic@borrower"].cpus == 8.0
    lender = {o.node_id: o for o in cluster.pending_offers("lender")}
    assert lender["l0"].cpus == 8.0  # 16 minus 8 withheld
    assert lender["l0"].mem == 8000.0
    # reclaim: converge both pools back to zero
    cluster.scale("borrower", {"mem": 0.0, "cpus": 0.0, "gpus": 0.0})
    cluster.scale("lender", {"mem": 0.0, "cpus": 0.0, "gpus": 0.0})
    assert "elastic@borrower" not in cluster.hosts
    assert {o.node_id: o for o in
            cluster.pending_offers("lender")}["l0"].cpus == 16.0


def test_mock_scale_drains_busy_elastic_host():
    from cook_tpu.cluster.base import TaskSpec

    clock = FakeClock()
    cluster = MockCluster("m", [], clock=clock)
    cluster.scale("p", {"mem": 8000.0, "cpus": 8.0, "gpus": 0.0})
    cluster.launch_tasks("p", [TaskSpec(
        task_id="t1", job_uuid="j1", user="u", command="c", mem=1000,
        cpus=2, gpus=0, node_id="elastic@p", hostname="elastic@p")])
    cluster.scale("p", {"mem": 0.0, "cpus": 0.0, "gpus": 0.0})
    # the running task keeps its (zero-capacity, draining) host
    assert "elastic@p" in cluster.hosts
    offers = {o.node_id: o for o in cluster.pending_offers("p")}
    assert offers["elastic@p"].cpus == 0.0  # clamped, never negative
    cluster.kill_task("t1")
    cluster.scale("p", {"mem": 0.0, "cpus": 0.0, "gpus": 0.0})
    assert "elastic@p" not in cluster.hosts


def test_k8s_scale_resize_request_and_cordon():
    clock = FakeClock()
    api = FakeKubeApi([
        KubeNode(name="n0", mem=16000, cpus=16, pool="lender"),
        KubeNode(name="n1", mem=16000, cpus=16, pool="lender"),
        KubeNode(name="b0", mem=16000, cpus=16, pool="borrower"),
    ])
    cluster = KubeCluster("k", api, clock)
    cluster.scale("borrower", {"mem": 20000.0, "cpus": 20.0, "gpus": 0.0})
    assert cluster.resize_requests[-1]["pool"] == "borrower"
    elastic = [n for n in api.list_nodes()
               if n.name.startswith("elastic-borrower-")]
    assert len(elastic) == 2  # ceil(20 / 16-cpu template nodes)
    # lender side: empty nodes cordoned, capacity leaves the offers
    before = len(cluster.pending_offers("lender"))
    cluster.scale("lender", {"mem": -16000.0, "cpus": -16.0, "gpus": 0.0})
    after = len(cluster.pending_offers("lender"))
    assert after == before - 1
    # reclaim: uncordon + drop the now-empty elastic nodes
    cluster.scale("lender", {"mem": 0.0, "cpus": 0.0, "gpus": 0.0})
    cluster.scale("borrower", {"mem": 0.0, "cpus": 0.0, "gpus": 0.0})
    assert len(cluster.pending_offers("lender")) == before
    assert not [n for n in api.list_nodes()
                if n.name.startswith("elastic-borrower-")]


def test_k8s_scale_prefix_sibling_pools_do_not_collide():
    """Pool 'gpu' must not claim (or shrink away) pool 'gpu-west's
    elastic nodes: 'elastic-gpu-west-0'.startswith('elastic-gpu-'), so
    ownership needs the node's pool, not just the name prefix."""
    clock = FakeClock()
    api = FakeKubeApi([
        KubeNode(name="g0", mem=16000, cpus=16, pool="gpu"),
        KubeNode(name="w0", mem=16000, cpus=16, pool="gpu-west"),
    ])
    cluster = KubeCluster("k", api, clock)
    cluster.scale("gpu-west", {"mem": 16000.0, "cpus": 16.0, "gpus": 0.0})
    assert [n.name for n in api.list_nodes()
            if n.name.startswith("elastic-gpu-west-")] == \
        ["elastic-gpu-west-0"]
    # converging pool "gpu" to zero must leave gpu-west's node alone
    cluster.scale("gpu", {"mem": 0.0, "cpus": 0.0, "gpus": 0.0})
    assert [n.name for n in api.list_nodes()
            if n.name.startswith("elastic-gpu-west-")] == \
        ["elastic-gpu-west-0"]


def test_k8s_resize_request_ring_skips_unchanged_targets():
    """reconcile() converges every interval; only target CHANGES may
    enter the bounded resize-request ring or no-ops would rotate real
    requests out before an external controller sees them."""
    clock = FakeClock()
    api = FakeKubeApi([KubeNode(name="n0", mem=16000, cpus=16, pool="p")])
    cluster = KubeCluster("k", api, clock)
    for _ in range(10):
        cluster.scale("p", {"mem": 0.0, "cpus": 0.0, "gpus": 0.0})
    assert cluster.resize_requests == []  # all-zero never-loaned: noise
    for _ in range(10):
        cluster.scale("p", {"mem": 8000.0, "cpus": 8.0, "gpus": 0.0})
    assert len(cluster.resize_requests) == 1
    cluster.scale("p", {"mem": 0.0, "cpus": 0.0, "gpus": 0.0})
    assert len(cluster.resize_requests) == 2  # shrink-to-zero IS a change


# ------------------------------------------------- planner + observability


def _two_pool_scheduler(clock=None, data_dir=None, elastic=True,
                        borrower_hosts=0):
    clock = clock or FakeClock()
    store = JobStore(clock=clock)
    journal = None
    if data_dir is not None:
        journal = persistence.attach_journal(
            store, str(data_dir / "journal.jsonl"))
    store.set_pool(Pool(name="lender"))
    store.set_pool(Pool(name="borrower"))
    hosts = [MockHost(node_id="l0", hostname="l0", mem=16000, cpus=16,
                      pool="lender")]
    hosts += [MockHost(node_id=f"b{i}", hostname=f"b{i}", mem=4000, cpus=4,
                       pool="borrower") for i in range(borrower_hosts)]
    cluster = MockCluster("m", hosts, clock=clock)
    txn = TransactionLog(store, journal=journal)
    scheduler = Scheduler(
        store, [cluster],
        SchedulerConfig(elastic=ElasticParams(enabled=elastic)),
        txn=txn)
    return store, cluster, scheduler, txn, journal


def test_planner_loans_idle_capacity_and_records():
    store, cluster, scheduler, txn, _ = _two_pool_scheduler()
    for _ in range(6):
        store.submit_jobs([make_job(user="alice", pool="borrower",
                                    mem=2000, cpus=2)])
    record = scheduler.elastic_cycle()
    assert record is not None and record.moves
    loan = record.moves[0]
    assert loan["kind"] == "loan" and loan["from"] == "lender" \
        and loan["to"] == "borrower"
    assert store.capacity_ledger[("lender", "borrower")]["cpus"] > 0
    # the committed deltas are durable transactions with recorded results
    assert record.txn_id in store.txn_results
    # converged cluster state: borrower gained an elastic host, lender's
    # offers shrank by the loaned amount
    offers = {o.node_id: o for o in cluster.pending_offers("borrower")}
    assert "elastic@borrower" in offers
    lender_spare = sum(o.cpus for o in cluster.pending_offers("lender"))
    assert lender_spare < 16.0
    # the decision is in the /debug/elastic ring
    plans = scheduler.elastic.recorder.records_json()
    assert plans and plans[-1]["txn_id"] == record.txn_id
    # ...and the next match cycle's record carries the plan linkage +
    # capacity snapshot (the /debug/cycles correlation satellite)
    borrower = store.pools["borrower"]
    scheduler.rank_cycle(borrower)
    scheduler.match_cycle(borrower)
    cycle = scheduler.recorder.records_json(pool="borrower")[-1]
    assert cycle["elastic_plan"] == record.plan_id
    assert cycle["pool_capacity"]["hosts"] >= 1
    assert cycle["pool_capacity"]["spare_cpus"] >= 0.0


def test_planner_no_op_with_single_pool():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="only"))
    cluster = MockCluster("m", [MockHost(node_id="h", hostname="h",
                                         mem=1000, cpus=1, pool="only")],
                          clock=clock)
    scheduler = Scheduler(store, [cluster],
                          SchedulerConfig(elastic=ElasticParams(
                              enabled=True)))
    assert scheduler.elastic_cycle() is None


def test_planner_solves_bucket_padded_no_recompile_storm():
    """Varying pool and queue counts must reuse a handful of padded
    programs — the CompileObservatory would flag elastic_plan churn
    exactly like any other op (the inducing acceptance test)."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    for i in range(6):
        store.set_pool(Pool(name=f"p{i}"))
    cluster = MockCluster("m", [
        MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=8000, cpus=8,
                 pool=f"p{i}") for i in range(6)], clock=clock)
    from cook_tpu.obs import DeviceTelemetry

    telemetry = DeviceTelemetry(update_memory_gauges=False,
                                storm_warmup=0)
    planner = CapacityPlanner(store, [cluster], TransactionLog(store),
                              ElasticParams(enabled=True),
                              telemetry=telemetry)
    rng = np.random.default_rng(0)
    for trial in range(24):
        queues = {}
        for i in range(int(rng.integers(2, 7))):
            jobs = [make_job(user="u", pool=f"p{i}", mem=100, cpus=1)
                    for _ in range(int(rng.integers(1, 50)))]
            queues[f"p{i}"] = types.SimpleNamespace(jobs=jobs)
        planner.plan_cycle(queues)
    stats = telemetry.observatory.stats().get("elastic_plan", {})
    # every trial fits the (8-pool, 64-job) bucket: ONE program, even
    # with the storm warmup grace disabled
    assert stats.get("programs", 99) == 1
    assert "elastic_plan" not in telemetry.observatory.storming_ops()


# ------------------------------------- reclaim-before-preemption + failover


def _pressure_lender(store, clock):
    """Running bob task on l0 (high DRU) + pending alice job that the
    shaved lender pool cannot place without help."""
    store.set_share(Share(user="bob", pool="lender",
                          resources=Resources(mem=100.0, cpus=1.0)))
    bob = make_job(user="bob", pool="lender", mem=8000, cpus=8)
    store.submit_jobs([bob])
    store.create_instance(bob.uuid, "task-bob", hostname="l0",
                          node_id="l0", compute_cluster="m")
    store.update_instance_state("task-bob", InstanceStatus.RUNNING, None)
    alice = make_job(user="alice", pool="lender", mem=8000, cpus=8)
    store.submit_jobs([alice])
    return bob, alice


def test_reclaim_returns_capacity_before_preemption_across_failover(
        tmp_path):
    """ISSUE-4 acceptance: lender loans to borrower; the leader dies;
    the promoted leader (journal-replayed ledger) sees lender demand
    return and reclaims BEFORE its victim search chooses anyone — the
    same cycle that would otherwise preempt finds spare-only decisions.
    """
    clock = FakeClock()
    store, cluster, scheduler, txn, journal = _two_pool_scheduler(
        clock=clock, data_dir=tmp_path)
    # borrower demand pulls a loan out of the idle lender
    for _ in range(4):
        store.submit_jobs([make_job(user="carol", pool="borrower",
                                    mem=3000, cpus=3)])
    record = scheduler.elastic_cycle()
    assert record.moves and store.outstanding_loans_from("lender")
    journal.close()

    # ---- leader failover: fresh process, fresh (reset) mock backend ----
    store2 = persistence.recover(str(tmp_path))
    assert store2.capacity_ledger == store.capacity_ledger
    cluster2 = MockCluster("m", [
        MockHost(node_id="l0", hostname="l0", mem=16000, cpus=16,
                 pool="lender")], clock=clock)
    scheduler2 = Scheduler(
        store2, [cluster2],
        SchedulerConfig(elastic=ElasticParams(enabled=True)),
        txn=TransactionLog(store2))
    # promotion reconcile (components.start_leader_duties): clusters
    # converge to the replayed ledger — lender offers are shaved again
    scheduler2.elastic.reconcile()
    loaned = store2.capacity_ledger[("lender", "borrower")]["cpus"]
    assert sum(o.cpus for o in cluster2.pending_offers("lender")) \
        == pytest.approx(16.0 - loaned)

    # lender regains demand
    bob, alice = _pressure_lender(store2, clock)
    lender = store2.pools["lender"]
    scheduler2.rank_cycle(lender)
    scheduler2.match_cycle(lender)  # can't place: spare is loaned out
    assert store2.jobs[alice.uuid].state.value == "waiting"

    # CONTROL: the same victim search WITHOUT the reclaimer picks bob
    spare = scheduler2.last_unmatched_offers["lender"]
    queue = scheduler2.pool_queues["lender"]
    control = rebalance_pool(store2, lender, queue.jobs, spare,
                             RebalancerParams())
    assert control and "task-bob" in control[0].task_ids

    # the real cycle reclaims first: no victims, ledger cleared,
    # capacity back in the lender's offers
    decisions = scheduler2.rebalance_cycle(lender)
    assert decisions == []
    assert store2.outstanding_loans_from("lender") == {}
    assert not store2.instances["task-bob"].status.terminal
    # the withheld capacity is back in the lender's offers (bob's task
    # lives in the store, not the reset mock backend, so the full host
    # shows free again)
    assert sum(o.cpus for o in cluster2.pending_offers("lender")) \
        == pytest.approx(16.0)
    # the reclaim decision is durable + in the ring
    kinds = [p["kind"] for p in scheduler2.elastic.recorder.records_json()]
    assert "reclaim-on-demand" in kinds
    # and the freed capacity places alice's job on the next cycle
    scheduler2.rank_cycle(lender)
    outcome = scheduler2.match_cycle(lender)
    assert any(j.uuid == alice.uuid for j, _ in outcome.matched)


def test_reclaim_txn_replay_is_consistent_after_second_failover(tmp_path):
    """A reclaim committed right before death must replay to the same
    ledger on the next leader (idempotent, never negative)."""
    clock = FakeClock()
    store, cluster, scheduler, txn, journal = _two_pool_scheduler(
        clock=clock, data_dir=tmp_path)
    txn.commit("pool/capacity-delta", {"moves": [
        {"kind": "loan", "from": "lender", "to": "borrower",
         "mem": 4000.0, "cpus": 4.0, "gpus": 0.0}]})
    txn.commit("pool/capacity-delta", {"moves": [
        {"kind": "reclaim", "from": "lender", "to": "borrower",
         "mem": 4000.0, "cpus": 4.0, "gpus": 0.0}]}, txn_id="reclaim-1")
    journal.close()
    store2 = persistence.recover(str(tmp_path))
    assert store2.capacity_ledger == {}
    # the retried reclaim (client retry against the new leader) dedupes
    out = TransactionLog(store2).commit(
        "pool/capacity-delta", {"moves": [
            {"kind": "reclaim", "from": "lender", "to": "borrower",
             "mem": 4000.0, "cpus": 4.0, "gpus": 0.0}]},
        txn_id="reclaim-1")
    assert out.duplicate
    assert store2.capacity_ledger == {}


# ------------------------------------------------------------ simulator A/B


def test_simulator_ab_elastic_lowers_queued_wait():
    """ISSUE-4 acceptance: imbalanced pools, p50 queued-job wait lower
    with the elastic planner enabled vs static pools."""
    from cook_tpu.sim.loadgen import imbalanced_pool_trace
    from cook_tpu.sim.simulator import SimConfig, Simulator

    jobs, hosts = imbalanced_pool_trace(busy_jobs=24, runtime_ms=60_000)

    def run(elastic_every):
        config = SimConfig(
            cycle_ms=30_000, max_cycles=60, elastic_every=elastic_every,
            pools=(("busy", "default"), ("idle", "default")),
            scheduler=SchedulerConfig(flight_recorder_capacity=64),
        )
        return Simulator(jobs, hosts, config).run()

    static = run(0)
    elastic = run(1)
    p50_static = float(np.percentile(static.queued_wait_ms(), 50))
    p50_elastic = float(np.percentile(elastic.queued_wait_ms(), 50))
    assert p50_elastic < p50_static
    assert any(p["moves"] for p in elastic.elastic_plans)
    # the loan shows up in the final ledger dump (idle never re-needed it)
    assert any(row["from"] == "idle" and row["to"] == "busy"
               for row in elastic.capacity_ledger)
    # every elastic match cycle carries the capacity snapshot
    assert all("pool_capacity" in r for r in elastic.cycle_records)


# ------------------------------------------------------------ REST surface


@pytest.fixture()
def elastic_server():
    from cook_tpu.rest.api import ApiConfig, CookApi
    from cook_tpu.rest.server import ServerThread

    store, cluster, scheduler, txn, _ = _two_pool_scheduler()
    api = CookApi(store, scheduler, ApiConfig(admins=("admin",)), txn=txn)
    srv = ServerThread(api).start()
    srv.store = store
    srv.scheduler = scheduler
    yield srv
    srv.stop()


def test_debug_elastic_endpoint(elastic_server):
    srv = elastic_server
    for _ in range(6):
        srv.store.submit_jobs([make_job(user="alice", pool="borrower",
                                        mem=2000, cpus=2)])
    record = srv.scheduler.elastic_cycle()
    r = requests.get(f"{srv.url}/debug/elastic",
                     headers={"X-Cook-Requesting-User": "u"})
    assert r.status_code == 200
    body = r.json()
    assert body["enabled"] is True
    assert body["ledger"] and body["ledger"][0]["from"] == "lender"
    assert body["net"]["borrower"]["cpus"] > 0
    assert body["net"]["lender"]["cpus"] < 0
    assert body["plans"][-1]["plan"] == record.plan_id
    assert body["plans"][-1]["moves"]
    # kind filter + limit validation
    r = requests.get(f"{srv.url}/debug/elastic?kind=interval&limit=1",
                     headers={"X-Cook-Requesting-User": "u"})
    assert r.status_code == 200 and len(r.json()["plans"]) == 1
    r = requests.get(f"{srv.url}/debug/elastic?limit=x",
                     headers={"X-Cook-Requesting-User": "u"})
    assert r.status_code == 400


def test_loaned_gauge_and_metrics_exposition(elastic_server):
    srv = elastic_server
    for _ in range(6):
        srv.store.submit_jobs([make_job(user="alice", pool="borrower",
                                        mem=2000, cpus=2)])
    srv.scheduler.elastic_cycle()
    r = requests.get(f"{srv.url}/metrics")
    assert r.status_code == 200
    text = r.text
    assert "cook_elastic_loaned{" in text
    assert 'from="lender"' in text and 'to="borrower"' in text
    assert "cook_elastic_plans" in text
    # the reclaim histogram is registered (TYPE line) even before any
    # reclaim has been observed
    assert "cook_elastic_reclaim_seconds" in text


# ---------------------------------------------------- capacity vs pool-move


def test_capacity_deltas_racing_pool_moves_stay_consistent():
    """Loans/reclaims and job pool-moves hammer the same commit
    pipeline concurrently; the ledger must stay non-negative and every
    job must land in exactly one pool."""
    store = _ledger_store()
    txn = TransactionLog(store)
    jobs = [make_job(user="u", pool="borrower", mem=10, cpus=1)
            for _ in range(40)]
    store.submit_jobs(jobs)
    errors = []

    def capacity_churn():
        try:
            for i in range(50):
                txn.commit("pool/capacity-delta", {"moves": [
                    {"kind": "loan", "from": "lender", "to": "borrower",
                     "mem": 100.0, "cpus": 1.0, "gpus": 0.0}]})
                txn.commit("pool/capacity-delta", {"moves": [
                    {"kind": "reclaim", "from": "lender", "to": "borrower",
                     "mem": 100.0, "cpus": 1.0, "gpus": 0.0}]})
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def mover():
        try:
            for job in jobs:
                txn.commit("job/pool-move",
                           {"uuid": job.uuid, "pool": "lender"})
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=capacity_churn),
               threading.Thread(target=mover)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert store.capacity_ledger == {}  # every loan was reclaimed
    for job in jobs:
        assert store.jobs[job.uuid].pool == "lender"
    # the ledger event stream replays to the same end state
    replayed = JobStore()
    replayed.set_pool(Pool(name="lender"))
    replayed.set_pool(Pool(name="borrower"))
    events = [json.loads(e.to_json()) for e in store.snapshot_events()]
    persistence.apply_journal(replayed, events)
    assert replayed.capacity_ledger == store.capacity_ledger
