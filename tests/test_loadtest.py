"""tools/loadtest.py + sim/loadgen.rest_traffic_trace: the sustained
control-plane load harness and its shared reproducible traffic shape."""
import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))

import loadtest  # noqa: E402

from cook_tpu.sim.loadgen import (  # noqa: E402
    rest_traffic_trace,
    traffic_trace_jobs,
)


# ------------------------------------------------------------ trace shape


def test_trace_is_deterministic_by_seed():
    a = rest_traffic_trace(duration_s=5.0, rps=40.0, seed=7)
    b = rest_traffic_trace(duration_s=5.0, rps=40.0, seed=7)
    assert a == b
    c = rest_traffic_trace(duration_s=5.0, rps=40.0, seed=8)
    assert a != c


def test_trace_ops_well_formed():
    ops = rest_traffic_trace(duration_s=5.0, rps=60.0, seed=3)
    assert len(ops) > 100  # ~300 expected at 60 rps over 5 s
    submits = set()
    last_offset = 0.0
    for i, op in enumerate(ops):
        assert op.offset_s >= last_offset
        last_offset = op.offset_s
        assert op.kind in ("submit", "query", "kill")
        if op.kind == "submit":
            assert op.spec["command"] == "true"
            submits.add(i)
        else:
            # query/kill always target an EARLIER submit
            assert op.ref in submits and op.ref < i
    # the mix produced all three kinds
    kinds = {op.kind for op in ops}
    assert kinds == {"submit", "query", "kill"}


def test_trace_is_bursty():
    """Burst windows must carry visibly more arrivals per second than
    the off-burst base — the thundering-herd shape is the point."""
    ops = rest_traffic_trace(duration_s=20.0, rps=50.0, seed=1,
                             burst_every_s=2.0, burst_len_s=0.4,
                             burstiness=4.0)
    in_burst = sum(1 for op in ops if (op.offset_s % 2.0) < 0.4)
    out_burst = len(ops) - in_burst
    burst_rate = in_burst / (20.0 * 0.2)        # 20% of wall is burst
    base_rate = out_burst / (20.0 * 0.8)
    assert burst_rate > 2.0 * base_rate


def test_trace_converts_to_sim_jobs():
    ops = rest_traffic_trace(duration_s=5.0, rps=40.0, seed=2)
    jobs = traffic_trace_jobs(ops, runtime_ms=500)
    assert len(jobs) == sum(1 for op in ops if op.kind == "submit")
    assert all(j.runtime_ms == 500 for j in jobs)
    # arrival offsets survive the conversion
    assert jobs[0].submit_time_ms == int(ops[0].offset_s * 1000)


# --------------------------------------------------------- live harness


@pytest.fixture(scope="module")
def plane():
    from cook_tpu.rest.server import InprocessControlPlane

    plane = InprocessControlPlane().start()
    yield plane
    plane.stop()


def test_loadtest_reports_commit_ack_and_attribution(plane):
    report = loadtest.run_loadtest(
        plane.url, rps=40.0, duration_s=1.0, mode="closed", workers=2,
        seed=5, warmup=3)
    assert report["errors"] == 0
    ack = report["commit_ack"]
    assert ack["count"] > 0
    assert ack["p50_ms"] > 0 and ack["p99_ms"] >= ack["p50_ms"]
    # the run closes with the server's own attribution
    contention = report["contention"]
    assert contention["store_lock"]["acquisitions"] > 0
    # the in-process plane journals every commit: fsyncs happened
    assert contention["journal"]["fsyncs"] > 0
    assert "POST /jobs" in contention["endpoints"]


def test_open_loop_paces_arrivals(plane):
    """Open loop takes at least the trace's span of wall time (requests
    start at their offsets; closed loop would finish much sooner)."""
    import time

    t0 = time.perf_counter()
    report = loadtest.run_loadtest(
        plane.url, rps=30.0, duration_s=1.0, mode="open", workers=8,
        seed=6)
    wall = time.perf_counter() - t0
    assert report["errors"] == 0
    assert wall >= 0.5  # paced, not back-to-back


def test_inprocess_smoke_round_trip():
    """What bench.py's control_plane phase runs: a fresh in-process
    plane, driven and torn down."""
    report = loadtest.run_inprocess(rps=30.0, duration_s=0.5,
                                    mode="closed", workers=1, seed=9,
                                    warmup=2)
    assert report["errors"] == 0
    assert report["commit_ack"]["count"] > 0
