"""Concurrency: parallel REST clients + scheduler cycles against one store
must preserve the state-machine invariants and columnar consistency."""
import threading
import time

import requests

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread
from cook_tpu.scheduler.core import Scheduler
from tests.conftest import FakeClock
from tests.test_state_fuzz import check_invariants


def test_concurrent_clients_and_cycles():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m",
        [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=16000, cpus=32)
         for i in range(4)],
        clock=clock)
    scheduler = Scheduler(store, [cluster])
    srv = ServerThread(CookApi(store, scheduler, ApiConfig())).start()
    stop = threading.Event()
    errors: list = []

    def client(n):
        session = requests.Session()
        headers = {"X-Cook-Requesting-User": f"user{n}"}
        mine = []
        while not stop.is_set():
            try:
                r = session.post(
                    f"{srv.url}/jobs",
                    json={"jobs": [{"command": "x", "mem": 100, "cpus": 1,
                                    "expected_runtime": 2000}]},
                    headers=headers, timeout=5)
                assert r.status_code == 201, r.text
                mine.append(r.json()["jobs"][0])
                if len(mine) % 3 == 0:
                    session.delete(f"{srv.url}/jobs",
                                   params={"job": mine[-1]},
                                   headers=headers, timeout=5)
            except Exception as e:  # noqa: BLE001 — collected for assert
                errors.append(repr(e))
                return

    def cycles():
        pool = store.pools["default"]
        while not stop.is_set():
            try:
                scheduler.rank_cycle(pool)
                scheduler.match_cycle(pool)
                clock.advance(500)
                cluster.advance_to(clock())
            except Exception as e:  # noqa: BLE001
                errors.append("cycle:" + repr(e))
                return

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    threads.append(threading.Thread(target=cycles))
    for t in threads:
        t.start()
    time.sleep(4)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    srv.stop()
    assert not errors, errors[:3]
    check_invariants(store)
    assert scheduler.columnar.consistent_with_store()
    assert len(store.jobs) > 50  # the hammer actually hammered
