"""The DCN scale-out path: two real processes, jax.distributed, one
global mesh, the pool-sharded match solve spanning both (SURVEY §2.4
comm-backend row; examples/multihost_dryrun.py is the recipe)."""
import socket
import subprocess
import sys

import pytest


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _multiprocess_backend_supported() -> tuple[bool, str]:
    """Capability probe: can this jax build run multi-process (DCN)
    computations on the available backend?  jax 0.4.x's CPU PJRT client
    raises `Multiprocess computations aren't implemented on the CPU
    backend` inside the coordinator dryrun — an environment limitation
    (docs/status.md), not a product bug, so the test self-skips with
    the probe's evidence instead of failing tier-1 forever."""
    import jax

    platform = jax.devices()[0].platform
    version = getattr(jax, "__version_info__", (0, 0, 0))
    if platform == "cpu" and version < (0, 5):
        return False, (
            f"jax {jax.__version__} CPU backend lacks multiprocess "
            f"computations (PJRT: 'Multiprocess computations aren't "
            f"implemented on the CPU backend'); needs real multi-host "
            f"hardware or jax >= 0.5")
    return True, ""


def test_two_process_dcn_dryrun():
    supported, reason = _multiprocess_backend_supported()
    if not supported:
        pytest.skip(reason)
    port = free_port()
    out = subprocess.run(
        [sys.executable, "examples/multihost_dryrun.py", "--workers", "2",
         "--coordinator", f"127.0.0.1:{port}"],
        capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "multihost dryrun OK" in out.stdout
    # both processes saw the full 8-device mesh and placed their shards
    assert out.stdout.count("mesh 8 devices across 2 processes") == 2
