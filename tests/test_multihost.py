"""The DCN scale-out path: two real processes, jax.distributed, one
global mesh, the pool-sharded match solve spanning both (SURVEY §2.4
comm-backend row; examples/multihost_dryrun.py is the recipe)."""
import socket
import subprocess
import sys


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_dcn_dryrun():
    port = free_port()
    out = subprocess.run(
        [sys.executable, "examples/multihost_dryrun.py", "--workers", "2",
         "--coordinator", f"127.0.0.1:{port}"],
        capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "multihost dryrun OK" in out.stdout
    # both processes saw the full 8-device mesh and placed their shards
    assert out.stdout.count("mesh 8 devices across 2 processes") == 2
