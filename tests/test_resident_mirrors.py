"""Resident mirrors for the rebalancer's victim tensors and the elastic
planner's demand/capacity tensors (scheduler/device_state.ResidentRows):
the >= 90% warm-cycle transfer floor on BOTH families, decision/plan
parity with the mirror on vs off, O(delta) scatters, the content-keyed
rebuild ladder (cold / width-changed / bucket-growth), perm + whole-array
caching, and the /debug/device row_mirrors surface."""
import types

import numpy as np

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.elastic import CapacityPlanner, ElasticParams
from cook_tpu.models.entities import (
    DEFAULT_USER,
    InstanceStatus,
    Job,
    Pool,
    Resources,
    Share,
)
from cook_tpu.models.store import JobStore
from cook_tpu.obs import data_plane
from cook_tpu.scheduler.device_state import ResidentRows, snapshot_all
from cook_tpu.scheduler.rebalancer import RebalancerParams, rebalance_pool
from cook_tpu.txn import TransactionLog

from conftest import FakeClock, make_job


def fam_h2d(family):
    return data_plane.LEDGER.family_totals().get(
        family, {}).get("h2d_bytes", 0)


# ------------------------------------------------------------ rebalancer


def _rebalance_rig(n_hosts=8, tasks_per_host=4):
    """Hog users holding every host (test_rebalancer_fast fixture
    family): the cycle-START victim tensors are the mirror's payload."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=400, cpus=4, gpus=1)))
    for h in range(n_hosts):
        for k in range(tasks_per_host):
            job = make_job(user=f"hog{k % 2}", mem=300 + 10 * h, cpus=3)
            store.submit_jobs([job])
            store.create_instance(job.uuid, f"t-{h}-{k}",
                                  hostname=f"h{h}", node_id=f"h{h}",
                                  compute_cluster="m")
    spare = {f"h{h}": Resources(mem=50.0, cpus=1.0)
             for h in range(n_hosts)}
    return clock, store, spare


def _pending(store, n=4):
    jobs = [make_job(user=f"starved{i}", mem=300, cpus=2)
            for i in range(n)]
    store.submit_jobs(jobs)
    return jobs


def _decision_sig(decisions, pending):
    # pending-queue POSITION, not uuid: make_job uuids are random
    order = {job.uuid: i for i, job in enumerate(pending)}
    return [(order[d.job.uuid], d.hostname, sorted(d.task_ids))
            for d in decisions]


def test_rebalancer_warm_cycles_cut_h2d_by_90_percent():
    """THE acceptance bar (rebalance-state family): a warm
    unchanged-fleet cycle moves >= 90% fewer FAM_REBALANCE H2D bytes
    than the cold rebuild cycle."""
    _, store, spare = _rebalance_rig()
    params = RebalancerParams(safe_dru_threshold=0.0, min_dru_diff=0.01,
                              max_preemption=8, resident=True)
    mirror = ResidentRows("rebalance:test",
                          family=data_plane.FAM_REBALANCE)
    pool = store.pools["default"]

    m0 = fam_h2d(data_plane.FAM_REBALANCE)
    rebalance_pool(store, pool, [], dict(spare), params, resident=mirror)
    cold = fam_h2d(data_plane.FAM_REBALANCE) - m0
    assert cold > 0
    assert mirror.last["rebuild"] is True
    assert mirror.last["reason"] == "cold"
    for _ in range(2):
        m0 = fam_h2d(data_plane.FAM_REBALANCE)
        rebalance_pool(store, pool, [], dict(spare), params,
                       resident=mirror)
        warm = fam_h2d(data_plane.FAM_REBALANCE) - m0
        assert mirror.last["rebuild"] is False
        assert mirror.last["delta_rows"] == 0
        assert warm <= 0.1 * cold, (warm, cold)


def test_rebalancer_decisions_identical_resident_on_off():
    """Residency is a transfer optimization, never a decision change:
    identical preemption decisions (job, host, victims) with the mirror
    on or off, across cold, warm, and post-termination cycles."""
    def run(resident_on):
        _, store, spare = _rebalance_rig(n_hosts=6, tasks_per_host=3)
        params = RebalancerParams(safe_dru_threshold=0.0,
                                  min_dru_diff=0.01, max_preemption=10,
                                  resident=resident_on)
        mirror = (ResidentRows(f"rebalance:parity-{resident_on}",
                               family=data_plane.FAM_REBALANCE)
                  if resident_on else None)
        pool = store.pools["default"]
        sigs = []
        for i in range(3):
            if i == 2:
                store.update_instance_state("t-0-0",
                                            InstanceStatus.SUCCESS)
            pending = _pending(store, n=3)
            decisions = rebalance_pool(store, pool, pending, dict(spare),
                                       params, resident=mirror)
            sigs.append(_decision_sig(decisions, pending))
            store.kill_jobs([job.uuid for job in pending])
        return sigs

    on, off = run(True), run(False)
    assert any(on), "scenario must produce preemptions"
    assert on == off


def test_rebalancer_termination_is_delta_scatter_not_rebuild():
    """A finished task's row rides the donated-buffer scatter: no
    rebuild, O(changed-rows) delta, still under the 10% byte bar."""
    _, store, spare = _rebalance_rig()
    params = RebalancerParams(safe_dru_threshold=0.0, min_dru_diff=0.01,
                              max_preemption=8, resident=True)
    mirror = ResidentRows("rebalance:delta",
                          family=data_plane.FAM_REBALANCE)
    pool = store.pools["default"]
    m0 = fam_h2d(data_plane.FAM_REBALANCE)
    rebalance_pool(store, pool, [], dict(spare), params, resident=mirror)
    cold = fam_h2d(data_plane.FAM_REBALANCE) - m0
    rebalance_pool(store, pool, [], dict(spare), params, resident=mirror)
    store.update_instance_state("t-0-0", InstanceStatus.SUCCESS)
    m0 = fam_h2d(data_plane.FAM_REBALANCE)
    rebalance_pool(store, pool, [], dict(spare), params, resident=mirror)
    delta_bytes = fam_h2d(data_plane.FAM_REBALANCE) - m0
    assert mirror.last["rebuild"] is False
    # the terminated task's row plus its USER's rows (the shared DRU
    # trajectory shifts for every task the user still runs) — here one
    # hog owns half the fleet, so up to 16 of 32 rows move, never all
    assert 1 <= mirror.last["delta_rows"] <= 16
    assert delta_bytes < cold, (delta_bytes, cold)


# --------------------------------------------------------------- elastic


def _elastic_rig(n_pools=4, queue_len=16):
    store = JobStore(clock=lambda: 1_000_000)
    for i in range(n_pools):
        store.set_pool(Pool(name=f"p{i}"))
    cluster = MockCluster("m", [
        MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=8000.0, cpus=8.0,
                 pool=f"p{i}") for i in range(n_pools)],
        clock=store.clock)

    def job(pool, k):
        return Job(uuid=f"el-{pool}-{k}", user="u", pool=pool,
                   priority=50,
                   resources=Resources(mem=100.0 + k, cpus=1.0),
                   command="true")

    # last pool idles: a lender
    queues = {f"p{i}": types.SimpleNamespace(
        jobs=[job(f"p{i}", k) for k in range(queue_len)])
        for i in range(n_pools - 1)}
    return store, cluster, queues, job


def test_elastic_warm_plans_cut_h2d_by_90_percent():
    """The same bar on the elastic-plan family: unchanged queues replan
    from the resident demand/capacity tensors."""
    store, cluster, queues, _ = _elastic_rig()
    planner = CapacityPlanner(store, [cluster], TransactionLog(store),
                              ElasticParams(enabled=True, resident=True))
    m0 = fam_h2d(data_plane.FAM_ELASTIC)
    assert planner.plan_cycle(queues) is not None
    cold = fam_h2d(data_plane.FAM_ELASTIC) - m0
    assert cold > 0
    assert planner._resident.last["reason"] == "cold"
    for _ in range(2):
        m0 = fam_h2d(data_plane.FAM_ELASTIC)
        planner.plan_cycle(queues)
        warm = fam_h2d(data_plane.FAM_ELASTIC) - m0
        assert planner._resident.last["rebuild"] is False
        assert warm <= 0.1 * cold, (warm, cold)


def test_elastic_plans_identical_resident_on_off():
    def run(resident_on):
        store, cluster, queues, job = _elastic_rig()
        planner = CapacityPlanner(
            store, [cluster], TransactionLog(store),
            ElasticParams(enabled=True, resident=resident_on))
        out = []
        for i in range(3):
            if i == 2:
                queues["p0"].jobs.append(job("p0", 99))
            record = planner.plan_cycle(queues)
            out.append((record.demand, record.moves, record.unmet))
        return out

    assert run(True) == run(False)


def test_elastic_queue_growth_within_bucket_is_one_delta_row():
    """One pool's queue growing inside its padded job bucket scatters
    exactly that pool's demand row — the other pools' rows are content
    hits."""
    store, cluster, queues, job = _elastic_rig()
    planner = CapacityPlanner(store, [cluster], TransactionLog(store),
                              ElasticParams(enabled=True, resident=True))
    planner.plan_cycle(queues)
    planner.plan_cycle(queues)
    queues["p1"].jobs.append(job("p1", 99))
    planner.plan_cycle(queues)
    assert planner._resident.last["rebuild"] is False
    assert planner._resident.last["delta_rows"] == 1


def test_elastic_queue_bucket_growth_rebuilds_width_changed():
    """The demand columns carry the padded queue axis in their trailing
    shape: a queue outgrowing its j_pad bucket changes the column width
    and must rebuild (reason width-changed), never serve stale rows."""
    store, cluster, queues, job = _elastic_rig(queue_len=8)
    planner = CapacityPlanner(store, [cluster], TransactionLog(store),
                              ElasticParams(enabled=True, resident=True))
    planner.plan_cycle(queues)
    # push p0 past the shared j_pad bucket
    queues["p0"].jobs.extend(job("p0", 100 + k) for k in range(128))
    planner.plan_cycle(queues)
    assert planner._resident.last["rebuild"] is True
    assert planner._resident.last["reason"] == "width-changed"


# ------------------------------------------------- ResidentRows contract


def _cols(vals):
    return {"a": np.asarray(vals, dtype=np.float32),
            "b": np.arange(len(vals), dtype=np.int32)}


def test_rebuild_ladder_reasons():
    rows = ResidentRows("ladder")
    _, s = rows.build(["k0", "k1"], _cols([1.0, 2.0]), out_len=4)
    assert (s["rebuild"], s["reason"]) == (True, "cold")
    # column set change -> width-changed
    _, s = rows.build(["k0"], {"a": np.zeros(1, np.float32)}, out_len=4)
    assert (s["rebuild"], s["reason"]) == (True, "width-changed")
    # key count past the row bucket -> bucket-growth
    keys = [f"g{i}" for i in range(130)]
    _, s = rows.build(keys, {"a": np.arange(130, dtype=np.float32)},
                      out_len=256)
    assert (s["rebuild"], s["reason"]) == (True, "bucket-growth")


def test_content_hit_moves_zero_rows_and_caches_perm():
    rows = ResidentRows("warm", family=data_plane.FAM_OTHER)
    out1, s1 = rows.build(["x", "y"], _cols([3.0, 4.0]), out_len=8)
    assert s1["delta_rows"] == 2
    m0 = fam_h2d(data_plane.FAM_OTHER)
    out2, s2 = rows.build(["x", "y"], _cols([3.0, 4.0]), out_len=8)
    assert s2["rebuild"] is False
    assert s2["delta_rows"] == 0
    # byte-identical content + stable layout: neither rows nor the perm
    # re-upload on the warm build
    assert fam_h2d(data_plane.FAM_OTHER) == m0
    np.testing.assert_array_equal(np.asarray(out2["a"])[:2], [3.0, 4.0])
    # pad rows gather the all-zero row
    assert not np.asarray(out2["a"])[2:].any()
    # gathers return FRESH arrays (safe against later donation)
    assert out1["a"] is not out2["a"]


def test_changed_row_scatters_only_that_row():
    rows = ResidentRows("delta")
    rows.build(["x", "y", "z"], _cols([1.0, 2.0, 3.0]), out_len=4)
    out, s = rows.build(["x", "y", "z"], _cols([1.0, 9.0, 3.0]),
                        out_len=4)
    assert s["rebuild"] is False
    assert s["delta_rows"] == 1
    np.testing.assert_array_equal(np.asarray(out["a"])[:3],
                                  [1.0, 9.0, 3.0])


def test_key_churn_reuses_slots_without_rebuild():
    """Departed keys' slots recycle LRU-first: a rolling key window
    churns through the bucket with delta-sized scatters, no rebuild."""
    rows = ResidentRows("churn")
    rows.build([f"k{i}" for i in range(48)],
               {"a": np.arange(48, dtype=np.float32)}, out_len=64)
    for step in (1, 2, 3):
        keys = [f"k{i}" for i in range(step * 16, step * 16 + 48)]
        _, s = rows.build(
            keys, {"a": np.arange(step * 16, step * 16 + 48,
                                  dtype=np.float32)}, out_len=64)
        assert s["rebuild"] is False, step
        assert s["delta_rows"] == 16, step


def test_whole_array_reuses_identical_content():
    rows = ResidentRows("arrays")
    a = np.arange(16, dtype=np.float32)
    d1 = rows.whole_array("supply", a)
    d2 = rows.whole_array("supply", a.copy())
    assert d1 is d2
    d3 = rows.whole_array("supply", a + 1)
    assert d3 is not d1
    np.testing.assert_array_equal(np.asarray(d3), a + 1)


def test_invalidate_forces_cold_rebuild():
    rows = ResidentRows("inval")
    rows.build(["k"], {"a": np.ones(1, np.float32)}, out_len=2)
    rows.invalidate()
    _, s = rows.build(["k"], {"a": np.ones(1, np.float32)}, out_len=2)
    assert (s["rebuild"], s["reason"]) == (True, "cold")


# ---------------------------------------------------------- debug surface


def test_snapshot_all_lists_row_mirrors():
    mirror = ResidentRows("rebalance:debug",
                          family=data_plane.FAM_REBALANCE)
    mirror.build(["t1", "t2"], _cols([1.0, 2.0]), out_len=4)
    mirror.whole_array("spare", np.ones(3, np.float32))
    snap = snapshot_all()
    assert snap["enabled"]
    mine = [r for r in snap["row_mirrors"]
            if r["name"] == "rebalance:debug"]
    assert len(mine) == 1
    row = mine[0]
    assert row["family"] == data_plane.FAM_REBALANCE
    assert row["resident_bytes"] > 0
    assert row["slots"] == 2
    assert set(row["columns"]) == {"a", "b"}
    assert row["arrays"]["spare"] > 0
    assert row["last"]["rebuild"] is True
