"""Moderate-scale simulator runs (BASELINE config 1 shape, 1k jobs x 100
nodes): exact-kernel vs fast-chunked-kernel replay must agree on packing
quality, and both must keep the cluster busy."""
import numpy as np

from cook_tpu.models.entities import JobState
from cook_tpu.scheduler.core import SchedulerConfig
from cook_tpu.scheduler.matcher import MatchConfig
from cook_tpu.sim.simulator import SimConfig, Simulator, synth_trace


def run_once(chunk: int):
    jobs, hosts = synth_trace(
        2000, 100, n_users=20, seed=7,
        mean_runtime_ms=90_000, submit_span_ms=240_000,
    )
    config = SimConfig(
        cycle_ms=30_000,
        max_cycles=400,
        scheduler=SchedulerConfig(
            match=MatchConfig(chunk=chunk, max_jobs_considered=1000)
        ),
    )
    sim = Simulator(jobs, hosts, config)
    result = sim.run()
    assert all(
        sim.store.jobs[j.uuid].state == JobState.COMPLETED for j in jobs
    )
    return result, hosts


def test_config1_exact_vs_chunked_parity():
    exact, hosts = run_once(chunk=0)
    fast, _ = run_once(chunk=256)
    u_exact = exact.utilization(hosts)
    u_fast = fast.utilization(hosts)
    # both complete all jobs; utilization (packing quality proxy) within 1%
    assert u_exact > 0.05
    assert abs(u_fast - u_exact) / u_exact < 0.01
    # makespan parity: the chunked kernel shouldn't stretch the schedule
    assert fast.virtual_ms <= exact.virtual_ms * 1.05
