"""State-machine tests, modeled on the reference's schema tests
(/root/reference/scheduler/test/cook/test/schema.clj): instance transition
validity, job-state derivation, mea-culpa retry accounting, allowed-to-start
preconditions, and the store's transactional behavior."""
import pytest

from cook_tpu.models import reasons
from cook_tpu.models.entities import InstanceStatus as I
from cook_tpu.models.entities import JobState as J
from cook_tpu.models.state import (
    JobNotAllowedToStart,
    check_allowed_to_start,
    update_instance_state,
    valid_instance_transition,
)
from cook_tpu.models.store import TransactionVetoed
from tests.conftest import make_job


def test_instance_transitions():
    assert valid_instance_transition(I.UNKNOWN, I.RUNNING)
    assert valid_instance_transition(I.UNKNOWN, I.FAILED)
    assert valid_instance_transition(I.UNKNOWN, I.SUCCESS)
    assert valid_instance_transition(I.RUNNING, I.SUCCESS)
    assert valid_instance_transition(I.RUNNING, I.FAILED)
    # terminal states are sticky
    assert not valid_instance_transition(I.SUCCESS, I.FAILED)
    assert not valid_instance_transition(I.FAILED, I.RUNNING)
    assert not valid_instance_transition(I.RUNNING, I.UNKNOWN)


class TestStoreLifecycle:
    def test_submit_launch_success(self, store):
        job = make_job()
        store.submit_jobs([job])
        assert store.jobs[job.uuid].state == J.WAITING
        assert store.pending_jobs("default")[0].uuid == job.uuid

        inst = store.create_instance(job.uuid, "t1", hostname="h1")
        assert inst.status == I.UNKNOWN
        assert store.jobs[job.uuid].state == J.RUNNING
        assert not store.pending_jobs("default")

        store.update_instance_state("t1", I.RUNNING)
        assert store.jobs[job.uuid].state == J.RUNNING

        store.update_instance_state("t1", I.SUCCESS, reasons.NORMAL_EXIT)
        assert store.jobs[job.uuid].state == J.COMPLETED
        assert store.instances["t1"].status == I.SUCCESS

    def test_fail_with_retries_goes_back_to_waiting(self, store):
        job = make_job(max_retries=3)
        store.submit_jobs([job])
        store.create_instance(job.uuid, "t1", hostname="h1")
        store.update_instance_state("t1", I.RUNNING)
        store.update_instance_state("t1", I.FAILED, reasons.UNKNOWN)
        assert store.jobs[job.uuid].state == J.WAITING

    def test_fail_out_of_retries_completes(self, store):
        job = make_job(max_retries=1)
        store.submit_jobs([job])
        store.create_instance(job.uuid, "t1", hostname="h1")
        store.update_instance_state("t1", I.FAILED, reasons.UNKNOWN)
        assert store.jobs[job.uuid].state == J.COMPLETED

    def test_mea_culpa_failure_is_free(self, store):
        job = make_job(max_retries=1)
        store.submit_jobs([job])
        # preemption is mea-culpa: does not consume the single retry
        store.create_instance(job.uuid, "t1", hostname="h1")
        store.update_instance_state(
            "t1", I.FAILED, reasons.PREEMPTED_BY_REBALANCER
        )
        assert store.jobs[job.uuid].state == J.WAITING
        # a plain failure then consumes it
        store.create_instance(job.uuid, "t2", hostname="h2")
        store.update_instance_state("t2", I.FAILED, reasons.UNKNOWN)
        assert store.jobs[job.uuid].state == J.COMPLETED

    def test_mea_culpa_limit_exhausts(self, store):
        store.mea_culpa_limit = 2
        job = make_job(max_retries=1)
        store.submit_jobs([job])
        for i in range(3):
            store.create_instance(job.uuid, f"t{i}", hostname="h1")
            store.update_instance_state(
                f"t{i}", I.FAILED, reasons.PREEMPTED_BY_REBALANCER
            )
        # 3 mea-culpa failures - limit 2 = 1 consumed = max_retries
        assert store.jobs[job.uuid].state == J.COMPLETED

    def test_disable_mea_culpa_retries(self, store):
        job = make_job(max_retries=1, disable_mea_culpa_retries=True)
        store.submit_jobs([job])
        store.create_instance(job.uuid, "t1", hostname="h1")
        store.update_instance_state(
            "t1", I.FAILED, reasons.PREEMPTED_BY_REBALANCER
        )
        assert store.jobs[job.uuid].state == J.COMPLETED

    def test_per_reason_failure_limit(self, store):
        # scheduling-failed-on-host has failure-limit 3
        job = make_job(max_retries=1)
        store.submit_jobs([job])
        for i in range(3):
            store.create_instance(job.uuid, f"t{i}", hostname="h1")
            store.update_instance_state(
                f"t{i}", I.FAILED, reasons.REASONS_BY_NAME["scheduling-failed-on-host"]
            )
            assert store.jobs[job.uuid].state == J.WAITING
        store.create_instance(job.uuid, "t3", hostname="h1")
        store.update_instance_state(
            "t3", I.FAILED, reasons.REASONS_BY_NAME["scheduling-failed-on-host"]
        )
        assert store.jobs[job.uuid].state == J.COMPLETED

    def test_allowed_to_start_vetoes_double_launch(self, store):
        job = make_job()
        store.submit_jobs([job])
        store.create_instance(job.uuid, "t1", hostname="h1")
        with pytest.raises(TransactionVetoed):
            store.create_instance(job.uuid, "t2", hostname="h2")

    def test_completed_job_is_terminal(self, store):
        job = make_job()
        store.submit_jobs([job])
        store.kill_jobs([job.uuid])
        assert store.jobs[job.uuid].state == J.COMPLETED
        with pytest.raises(TransactionVetoed):
            store.create_instance(job.uuid, "t1", hostname="h1")

    def test_kill_emits_event_for_fanout(self, store):
        seen = []
        store.add_watcher(lambda e: seen.append(e))
        job = make_job()
        store.submit_jobs([job])
        store.create_instance(job.uuid, "t1", hostname="h1")
        store.update_instance_state("t1", I.RUNNING)
        store.kill_jobs([job.uuid])
        kinds = [e.kind for e in seen]
        assert "job/state" in kinds
        last = [e for e in seen if e.kind == "job/state"][-1]
        assert last.data.get("killed") is True
        # the live instance is still live: the fan-out consumer kills it
        assert store.instances["t1"].status == I.RUNNING

    def test_retry_revives_completed_job(self, store):
        job = make_job(max_retries=1)
        store.submit_jobs([job])
        store.create_instance(job.uuid, "t1", hostname="h1")
        store.update_instance_state("t1", I.FAILED, reasons.UNKNOWN)
        assert store.jobs[job.uuid].state == J.COMPLETED
        store.retry_job(job.uuid, 3)
        assert store.jobs[job.uuid].state == J.WAITING

    def test_retry_does_not_revive_successful_job(self, store):
        job = make_job(max_retries=1)
        store.submit_jobs([job])
        store.create_instance(job.uuid, "t1", hostname="h1")
        store.update_instance_state("t1", I.SUCCESS, reasons.NORMAL_EXIT)
        store.retry_job(job.uuid, 5)
        assert store.jobs[job.uuid].state == J.COMPLETED

    def test_duplicate_submit_rejected(self, store):
        job = make_job()
        store.submit_jobs([job])
        with pytest.raises(TransactionVetoed):
            store.submit_jobs([job])


def test_update_instance_state_invalid_transition_ignored():
    job = make_job()
    from cook_tpu.models.entities import Instance

    inst = Instance(task_id="t1", job_uuid=job.uuid, status=I.SUCCESS)
    upd = update_instance_state(job, [inst], "t1", I.FAILED, None)
    assert not upd.applied


def test_attempts_consumed_unknown_reason_counts():
    assert reasons.attempts_consumed_by_reasons([None, None]) == 2
    assert reasons.attempts_consumed_by_reasons([1002] * 5) == 0
    assert reasons.attempts_consumed_by_reasons([1002] * 7) == 2
    assert (
        reasons.attempts_consumed_by_reasons([1002] * 7,
                                             disable_mea_culpa_retries=True)
        == 7
    )


def test_check_allowed_to_start():
    from cook_tpu.models.entities import Instance, JobState

    job = make_job()
    check_allowed_to_start(job, [])
    done = Instance(task_id="t0", job_uuid=job.uuid, status=I.FAILED)
    check_allowed_to_start(job, [done])
    live = Instance(task_id="t1", job_uuid=job.uuid, status=I.RUNNING)
    with pytest.raises(JobNotAllowedToStart):
        check_allowed_to_start(job, [done, live])
    with pytest.raises(JobNotAllowedToStart):
        check_allowed_to_start(job.with_(state=JobState.COMPLETED), [])
