"""End-to-end sandbox access: executor writes files -> sidecar serves them
-> scheduler exposes output_url -> `cs ls/cat/tail` reads them
(reference: cs ls/cat/tail + sidecar file server integration)."""
import asyncio
import json
import threading

import pytest

from cook_tpu.client.cli import main as cli_main
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.executor.runner import ExecutorConfig, TaskRunner
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread, free_port
from cook_tpu.scheduler.core import Scheduler
from cook_tpu.sidecar.fileserver import FileServer
from tests.conftest import FakeClock, make_job


@pytest.fixture
def stack(tmp_path):
    """Scheduler + mock cluster whose sandbox URLs point at a real sidecar
    file server over the executor's real sandbox."""
    sandbox = tmp_path / "sandbox"

    # run the job's command with the real executor
    sink_updates = []
    runner = TaskRunner(
        "task-x", "echo line one && echo line two", sink_updates.append,
        ExecutorConfig(sandbox_dir=str(sandbox)),
    )
    runner.run()

    # sidecar file server over that sandbox
    fs_port = free_port()
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run_fs():
        asyncio.set_event_loop(loop)
        from aiohttp import web

        app_runner = web.AppRunner(FileServer(str(sandbox)).build_app())
        loop.run_until_complete(app_runner.setup())
        site = web.TCPSite(app_runner, "127.0.0.1", fs_port)
        loop.run_until_complete(site.start())
        started.set()
        loop.run_forever()

    threading.Thread(target=run_fs, daemon=True).start()
    assert started.wait(5)

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock", [MockHost(node_id="h0", hostname="h0", mem=4000, cpus=8)],
        clock=clock,
        sandbox_url_fn=lambda tid: f"http://127.0.0.1:{fs_port}",
    )
    scheduler = Scheduler(store, [cluster])
    api = CookApi(store, scheduler, ApiConfig())
    srv = ServerThread(api).start()

    job = make_job()
    store.submit_jobs([job])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)

    cfg = tmp_path / "cs.json"
    cfg.write_text(json.dumps(
        {"clusters": [{"name": "c1", "url": srv.url}]}))
    yield srv, job, str(cfg)
    srv.stop()
    loop.call_soon_threadsafe(loop.stop)


def cli(cfg, *argv):
    return cli_main(["--config", cfg, "--user", "alice", *argv])


def test_cli_ls(stack, capsys):
    srv, job, cfg = stack
    assert cli(cfg, "ls", job.uuid) == 0
    out = capsys.readouterr().out
    assert "stdout" in out and "stderr" in out


def test_cli_cat(stack, capsys):
    srv, job, cfg = stack
    assert cli(cfg, "cat", job.uuid, "stdout") == 0
    assert capsys.readouterr().out == "line one\nline two\n"


def test_cli_tail(stack, capsys):
    srv, job, cfg = stack
    assert cli(cfg, "tail", job.uuid, "stdout", "--bytes", "9") == 0
    assert capsys.readouterr().out == "line two\n"


def test_cli_cat_missing_file(stack, capsys):
    srv, job, cfg = stack
    assert cli(cfg, "cat", job.uuid, "nope") == 1
