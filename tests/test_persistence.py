"""Durability: snapshot round-trip, journal append, failover recovery."""
import json

from cook_tpu.models.entities import (
    Checkpoint,
    InstanceStatus,
    JobState,
    Pool,
    Quota,
    Resources,
    Share,
)
from cook_tpu.models.persistence import (
    attach_journal,
    load_snapshot,
    read_journal,
    snapshot,
)
from cook_tpu.models.store import JobStore
from tests.conftest import FakeClock, make_job


def populated_store(clock):
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    store.set_share(Share(user="default", pool="default",
                          resources=Resources(mem=1000, cpus=10, gpus=1)))
    store.set_quota(Quota(user="alice", pool="default",
                          resources=Resources(mem=float("inf"), cpus=50),
                          count=10))
    j1 = make_job(user="alice", checkpoint=Checkpoint(mode="auto",
                                                      location="us-east"))
    j2 = make_job(user="bob", max_retries=3)
    j3 = make_job(user="bob")
    store.submit_jobs([j1, j2, j3])
    store.create_instance(j1.uuid, "t1", hostname="h1", compute_cluster="c")
    store.update_instance_state("t1", InstanceStatus.RUNNING)
    store.create_instance(j2.uuid, "t2", hostname="h2")
    store.update_instance_state("t2", InstanceStatus.FAILED, 1002)
    store.dynamic_config["x"] = {"y": 1}
    return store, (j1, j2, j3)


def test_snapshot_roundtrip(tmp_path, clock):
    store, (j1, j2, j3) = populated_store(clock)
    path = str(tmp_path / "snap.json")
    snapshot(store, path)
    restored = load_snapshot(path, clock=clock)

    assert restored.jobs.keys() == store.jobs.keys()
    for uuid in store.jobs:
        assert restored.jobs[uuid] == store.jobs[uuid], uuid
    assert restored.instances == store.instances
    assert restored.get_share("anyone", "default").mem == 1000
    assert restored.get_quota("alice", "default").count == 10
    assert restored.dynamic_config == {"x": {"y": 1}}
    # indexes rebuilt: pending/running views work
    assert {j.uuid for j in restored.pending_jobs("default")} == {
        j2.uuid, j3.uuid
    }
    assert [j.uuid for j in restored.running_jobs("default")] == [j1.uuid]
    # the restored store keeps transacting where the old one left off
    restored.update_instance_state("t1", InstanceStatus.SUCCESS, 1000)
    assert restored.jobs[j1.uuid].state == JobState.COMPLETED


def test_journal_appends_events(tmp_path, clock):
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    jpath = str(tmp_path / "journal.jsonl")
    writer = attach_journal(store, jpath)
    job = make_job()
    store.submit_jobs([job])
    store.create_instance(job.uuid, "t1", hostname="h1")
    store.update_instance_state("t1", InstanceStatus.SUCCESS, 1000)
    writer.close()
    events = read_journal(jpath)
    kinds = [e["kind"] for e in events]
    assert kinds == ["job/created", "instance/created", "job/state",
                     "instance/status", "job/state"]
    # seq strictly increasing
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


def test_snapshot_plus_new_events(tmp_path, clock):
    """Failover flow: snapshot, keep journaling, new leader loads the
    snapshot and sees consistent sequence numbering."""
    store, (j1, j2, j3) = populated_store(clock)
    snap = str(tmp_path / "snap.json")
    snapshot(store, snap)
    restored = load_snapshot(snap, clock=clock)
    seen = []
    restored.add_watcher(lambda e: seen.append(e))
    restored.kill_jobs([j3.uuid])
    old_last = store.snapshot_events()[-1].seq
    assert seen[0].seq == old_last + 1


def test_journal_rotation(tmp_path, clock):
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    jpath = str(tmp_path / "j.jsonl")
    writer = attach_journal(store, jpath)
    store.submit_jobs([make_job()])
    assert read_journal(jpath)
    snapshot(store, str(tmp_path / "snap.json"))
    writer.rotate()
    assert read_journal(jpath) == []          # fresh journal
    assert read_journal(jpath + ".1")         # prefix preserved aside
    job2 = make_job()
    store.submit_jobs([job2])                 # writer still live post-rotate
    events = read_journal(jpath)
    assert events and events[0]["kind"] == "job/created"
    writer.close()
    # snapshot + fresh journal reconstruct: snapshot has job1, journal job2
    restored = load_snapshot(str(tmp_path / "snap.json"), clock=clock)
    assert len(restored.jobs) == 1
