"""Durability: snapshot round-trip, journal append, failover recovery."""
import json

from cook_tpu.models.entities import (
    Application,
    Checkpoint,
    InstanceStatus,
    JobState,
    Pool,
    Quota,
    Resources,
    Share,
)
from cook_tpu.models.persistence import (
    attach_journal,
    load_snapshot,
    read_journal,
    recover,
    snapshot,
)
from cook_tpu.models.store import JobStore
from tests.conftest import FakeClock, make_job


def populated_store(clock):
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    store.set_share(Share(user="default", pool="default",
                          resources=Resources(mem=1000, cpus=10, gpus=1)))
    store.set_quota(Quota(user="alice", pool="default",
                          resources=Resources(mem=float("inf"), cpus=50),
                          count=10))
    j1 = make_job(user="alice", checkpoint=Checkpoint(mode="auto",
                                                      location="us-east"),
                  application=Application(name="svc", version="1.2",
                                          workload_class="batch",
                                          workload_id="w-17"))
    j2 = make_job(user="bob", max_retries=3)
    j3 = make_job(user="bob")
    store.submit_jobs([j1, j2, j3])
    store.create_instance(j1.uuid, "t1", hostname="h1", compute_cluster="c")
    store.update_instance_state("t1", InstanceStatus.RUNNING)
    store.create_instance(j2.uuid, "t2", hostname="h2")
    store.update_instance_state("t2", InstanceStatus.FAILED, 1002)
    store.dynamic_config["x"] = {"y": 1}
    return store, (j1, j2, j3)


def test_snapshot_roundtrip(tmp_path, clock):
    store, (j1, j2, j3) = populated_store(clock)
    path = str(tmp_path / "snap.json")
    snapshot(store, path)
    restored = load_snapshot(path, clock=clock)

    assert restored.jobs.keys() == store.jobs.keys()
    for uuid in store.jobs:
        assert restored.jobs[uuid] == store.jobs[uuid], uuid
    assert restored.instances == store.instances
    assert restored.get_share("anyone", "default").mem == 1000
    assert restored.get_quota("alice", "default").count == 10
    assert restored.dynamic_config == {"x": {"y": 1}}
    # indexes rebuilt: pending/running views work
    assert {j.uuid for j in restored.pending_jobs("default")} == {
        j2.uuid, j3.uuid
    }
    assert [j.uuid for j in restored.running_jobs("default")] == [j1.uuid]
    # the restored store keeps transacting where the old one left off
    restored.update_instance_state("t1", InstanceStatus.SUCCESS, 1000)
    assert restored.jobs[j1.uuid].state == JobState.COMPLETED
    # application metadata survives the roundtrip (advisor finding r1)
    assert restored.jobs[j1.uuid].application == store.jobs[j1.uuid].application
    assert restored.jobs[j1.uuid].application.workload_id == "w-17"


def test_journal_appends_events(tmp_path, clock):
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    jpath = str(tmp_path / "journal.jsonl")
    writer = attach_journal(store, jpath)
    job = make_job()
    store.submit_jobs([job])
    store.create_instance(job.uuid, "t1", hostname="h1")
    store.update_instance_state("t1", InstanceStatus.SUCCESS, 1000)
    writer.close()
    events = read_journal(jpath)
    kinds = [e["kind"] for e in events]
    assert kinds == ["job/created", "instance/created", "job/state",
                     "instance/status", "job/state"]
    # seq strictly increasing
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


def test_snapshot_plus_new_events(tmp_path, clock):
    """Failover flow: snapshot, keep journaling, new leader loads the
    snapshot and sees consistent sequence numbering."""
    store, (j1, j2, j3) = populated_store(clock)
    snap = str(tmp_path / "snap.json")
    snapshot(store, snap)
    restored = load_snapshot(snap, clock=clock)
    seen = []
    restored.add_watcher(lambda e: seen.append(e))
    restored.kill_jobs([j3.uuid])
    old_last = store.snapshot_events()[-1].seq
    assert seen[0].seq == old_last + 1


def test_journal_rotation(tmp_path, clock):
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    jpath = str(tmp_path / "j.jsonl")
    writer = attach_journal(store, jpath)
    store.submit_jobs([make_job()])
    assert read_journal(jpath)
    snapshot(store, str(tmp_path / "snap.json"))
    writer.rotate()
    assert read_journal(jpath) == []          # fresh journal
    assert read_journal(jpath + ".1")         # prefix preserved aside
    job2 = make_job()
    store.submit_jobs([job2])                 # writer still live post-rotate
    events = read_journal(jpath)
    assert events and events[0]["kind"] == "job/created"
    writer.close()
    # snapshot + fresh journal reconstruct: snapshot has job1, journal job2
    restored = load_snapshot(str(tmp_path / "snap.json"), clock=clock)
    assert len(restored.jobs) == 1


def _same_state(a: JobStore, b: JobStore) -> None:
    assert b.jobs == a.jobs
    assert b.instances == a.instances
    assert b.groups == a.groups
    assert b.pools == a.pools
    assert b.shares == a.shares
    assert b.quotas == a.quotas
    assert b.dynamic_config == a.dynamic_config
    for pool in a.pools:
        assert ({j.uuid for j in b.pending_jobs(pool)}
                == {j.uuid for j in a.pending_jobs(pool)})
        assert ({j.uuid for j in b.running_jobs(pool)}
                == {j.uuid for j in a.running_jobs(pool)})


def test_recover_journal_only(tmp_path, clock):
    """With no snapshot at all, the journal alone reconstructs the store —
    events carry full post-transaction entity payloads."""
    store = JobStore(clock=clock)
    writer = attach_journal(store, str(tmp_path / "journal.jsonl"))
    store.set_pool(Pool(name="default"))
    store.set_share(Share(user="default", pool="default",
                          resources=Resources(mem=500, cpus=4, gpus=0)))
    store.set_quota(Quota(user="alice", pool="default",
                          resources=Resources(mem=1e9, cpus=100), count=7))
    j1 = make_job(user="alice",
                  application=Application(name="a", version="2"))
    j2 = make_job(user="bob")
    store.submit_jobs([j1, j2])
    store.create_instance(j1.uuid, "t1", hostname="h1")
    store.update_instance_state("t1", InstanceStatus.RUNNING)
    store.update_instance_progress("t1", 40, "halfway-ish")
    store.set_instance_output("t1", exit_code=None, sandbox_directory="/sb")
    store.update_dynamic_config({"rebalancer": {"max_preemption": 9}})
    store.retract_quota("alice", "default")
    writer.close()

    restored = recover(str(tmp_path), clock=clock)
    assert restored is not None
    _same_state(store, restored)
    assert restored.jobs[j1.uuid].application.name == "a"
    assert restored.instances["t1"].progress == 40
    assert restored.instances["t1"].sandbox_directory == "/sb"
    assert ("alice", "default") not in restored.quotas
    # sequence numbering resumes after the replayed suffix
    assert restored.last_seq() == store.last_seq()
    restored.kill_jobs([j2.uuid])
    assert restored.last_seq() == store.last_seq() + 1


def test_recover_snapshot_plus_journal_suffix(tmp_path, clock):
    """The ADVICE-r1 scenario: writes acknowledged after the snapshot fired
    must survive — snapshot + journal suffix = exact state."""
    store = JobStore(clock=clock)
    writer = attach_journal(store, str(tmp_path / "journal.jsonl"))
    store.set_pool(Pool(name="default"))
    j1 = make_job(user="alice")
    store.submit_jobs([j1])
    snapshot(store, str(tmp_path / "snapshot.json"))
    writer.rotate()
    # post-snapshot writes: only the journal has them
    j2 = make_job(user="bob", group_uuid=None)
    store.submit_jobs([j2])
    store.create_instance(j1.uuid, "t1", hostname="h1")
    store.update_instance_state("t1", InstanceStatus.RUNNING)
    store.update_instance_state("t1", InstanceStatus.SUCCESS, 1000)
    store.retry_job(j2.uuid, 5)
    writer.close()

    restored = recover(str(tmp_path), clock=clock)
    _same_state(store, restored)
    assert restored.jobs[j1.uuid].state == JobState.COMPLETED
    assert restored.jobs[j2.uuid].max_retries == 5
    assert restored.recovered_stats["journal_replayed"] > 0


def test_recover_tolerates_torn_tail(tmp_path, clock):
    store = JobStore(clock=clock)
    writer = attach_journal(store, str(tmp_path / "journal.jsonl"))
    store.set_pool(Pool(name="default"))
    j1 = make_job()
    store.submit_jobs([j1])
    writer.close()
    with open(tmp_path / "journal.jsonl", "a") as f:
        f.write('{"seq": 99, "kind": "job/created", "da')  # crash mid-write
    restored = recover(str(tmp_path), clock=clock)
    assert j1.uuid in restored.jobs


def test_recover_empty_dir_returns_none(tmp_path, clock):
    assert recover(str(tmp_path), clock=clock) is None


def test_torn_tail_repaired_before_reattach(tmp_path, clock):
    """Crash mid-write, restart, new acknowledged write, crash again: the
    second recovery must keep the new write.  (Without truncating the torn
    fragment before reattaching, the new event merges into one corrupt
    line and everything after the tear is silently dropped.)"""
    jpath = str(tmp_path / "journal.jsonl")
    store = JobStore(clock=clock)
    writer = attach_journal(store, jpath)
    store.set_pool(Pool(name="default"))
    j1 = make_job()
    store.submit_jobs([j1])
    writer.close()
    with open(jpath, "a") as f:
        f.write('{"seq": 77, "kind": "job/created", "da')  # torn write

    # run 2: recover, reattach, acknowledge another job, crash
    store2 = recover(str(tmp_path), clock=clock)
    writer2 = attach_journal(store2, jpath)
    j2 = make_job(user="bob")
    store2.submit_jobs([j2])
    writer2.close()

    # run 3: BOTH acknowledged jobs must be there
    store3 = recover(str(tmp_path), clock=clock)
    assert j1.uuid in store3.jobs
    assert j2.uuid in store3.jobs


def test_journal_writer_batched_fsync_default_and_group_sync(tmp_path):
    """The default journal config must actually bound durability: batched
    fsync ON by default (VERDICT weak #4 — the old default of 0 never
    fsynced, so "every acknowledged write survives" was a process-crash
    claim only), plus the group-commit sync() barrier the transaction
    pipeline acks through."""
    from cook_tpu.models.persistence import JournalWriter

    jpath = str(tmp_path / "journal.jsonl")
    writer = JournalWriter(jpath)
    assert writer.fsync_every > 0, "default journal never fsyncs"

    writer.write_line(json.dumps({"seq": 1, "kind": "x", "data": {}}))
    assert writer._dirty
    writer.sync()
    assert not writer._dirty, "sync() left flushed events unfsynced"
    writer.sync()  # idempotent no-op when clean

    # the periodic batch bound also fsyncs without an explicit sync()
    batched = JournalWriter(str(tmp_path / "j2.jsonl"), fsync_every=2)
    batched.write_line(json.dumps({"seq": 1, "kind": "x", "data": {}}))
    assert batched._dirty
    batched.write_line(json.dumps({"seq": 2, "kind": "x", "data": {}}))
    assert not batched._dirty
    batched.close()
    writer.close()
    assert [e["seq"] for e in read_journal(jpath)] == [1]
