"""Incident observatory: profile capture, bundle lifecycle, the
end-to-end fault→degrade→bundle→recovery drill, and the per-job
timeline reconstruction (cook_tpu/obs/incident.py + obs/profiling.py)."""
import json
import threading
import time

import pytest

from cook_tpu import faults
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import (
    InstanceStatus,
    Job,
    JobState,
    Pool,
    Resources,
)
from cook_tpu.models.store import JobStore
from cook_tpu.obs.incident import IncidentRecorder, job_timeline
from cook_tpu.obs.profiling import ProfileCapturer
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.scheduler.matcher import MatchConfig
from tests.conftest import FakeClock


# ------------------------------------------------------- profile capturer


class FakeProfiler:
    def __init__(self, fail_start=False):
        self.started = []
        self.stopped = 0
        self.fail_start = fail_start

    def start(self, log_dir):
        if self.fail_start:
            raise RuntimeError("no device")
        self.started.append(log_dir)

    def stop(self):
        self.stopped += 1


def _capturer(tmp_path, fake, **kw):
    kw.setdefault("default_duration_s", 0.05)
    return ProfileCapturer(base_dir=str(tmp_path), start_fn=fake.start,
                           stop_fn=fake.stop, **kw)


def test_profile_capture_is_single_flight_and_stops_itself(tmp_path):
    fake = FakeProfiler()
    capturer = _capturer(tmp_path, fake)
    first = capturer.capture(trigger="manual")
    assert first["started"] and len(fake.started) == 1
    second = capturer.capture()
    assert not second["started"]
    assert second["reason"] == "capture-in-flight"
    assert len(fake.started) == 1  # the in-flight capture was untouched
    deadline = time.monotonic() + 5.0
    while fake.stopped == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fake.stopped == 1  # the timer stopped it, nobody else had to
    assert capturer.status()["active"] is None
    assert capturer.status()["recent"][0]["completed"]
    # single-flight released: a new capture may start
    assert capturer.capture()["started"]


def test_profile_duration_clamped_and_errors_degrade(tmp_path):
    fake = FakeProfiler()
    capturer = _capturer(tmp_path, fake, max_duration_s=0.05)
    result = capturer.capture(3600.0)
    assert result["duration_s"] == 0.05
    broken = _capturer(tmp_path, FakeProfiler(fail_start=True))
    result = broken.capture()
    assert not result["started"]
    assert "profiler-error" in result["reason"]
    assert broken.status()["active"] is None  # nothing leaked open


def test_auto_profile_reason_filter_and_cooldown(tmp_path):
    fake = FakeProfiler()
    capturer = _capturer(tmp_path, fake, cooldown_s=3600.0)
    # non-latency-shaped reasons never profile
    result = capturer.maybe_capture_auto(["recompile-storm"])
    assert not result["started"]
    assert result["reason"] == "no-latency-shaped-reason"
    assert capturer.maybe_capture_auto(["device-degraded"])["started"]
    deadline = time.monotonic() + 5.0
    while fake.stopped == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    # second auto within the cooldown is suppressed even though the
    # capture slot is free again
    result = capturer.maybe_capture_auto(["device-degraded"])
    assert not result["started"]
    assert result["reason"] == "cooldown"


# ------------------------------------------------------ incident recorder


def _verdict(healthy, *reasons):
    return {"healthy": healthy, "status": "ok" if healthy else "degraded",
            "reasons": list(reasons), "degradations": [], "checks": {}}


def test_capture_fires_exactly_on_the_ok_to_degraded_edge():
    recorder = IncidentRecorder(cooldown_s=0.0)
    recorder.add_collector("evidence", lambda: {"n": 42})
    assert recorder.observe(_verdict(True)) is None
    bundle = recorder.observe(_verdict(False, "fsync-stall"))
    assert bundle is not None
    assert bundle["reasons"] == ["fsync-stall"]
    assert bundle["evidence"] == {"n": 42}
    # still degraded: no second capture
    assert recorder.observe(_verdict(False, "fsync-stall")) is None
    assert len(recorder.bundles()) == 1
    # recovery stamps the bundle
    assert recorder.observe(_verdict(True)) is None
    [summary] = recorder.bundles()
    assert summary["recovered_time"] is not None
    # a NEW degradation is a new incident
    assert recorder.observe(_verdict(False, "replication-lag")) is not None
    assert len(recorder.bundles()) == 2


def test_cooldown_suppresses_flapping_and_collector_errors_degrade():
    recorder = IncidentRecorder(cooldown_s=3600.0)

    def boom():
        raise RuntimeError("ring on fire")

    recorder.add_collector("broken", boom)
    assert recorder.observe(_verdict(False, "x")) is not None
    recorder.observe(_verdict(True))
    # flap back within the cooldown: suppressed
    assert recorder.observe(_verdict(False, "x")) is None
    assert len(recorder.bundles()) == 1
    bundle = recorder.get(recorder.bundles()[0]["id"])
    assert "RuntimeError" in bundle["broken"]["error"]


def test_cooldown_suppressed_edge_captures_after_cooldown_clears():
    """A sustained incident whose edge landed inside the cooldown must
    still get a bundle once the cooldown clears — deferred, not
    dropped."""
    recorder = IncidentRecorder(cooldown_s=0.15)
    assert recorder.observe(_verdict(False, "a")) is not None
    recorder.observe(_verdict(True))
    # new incident starts inside the cooldown: deferred
    assert recorder.observe(_verdict(False, "b")) is None
    assert len(recorder.bundles()) == 1
    time.sleep(0.2)
    # still degraded after the cooldown: the deferred capture fires
    bundle = recorder.observe(_verdict(False, "b"))
    assert bundle is not None and bundle["reasons"] == ["b"]
    # and only once
    assert recorder.observe(_verdict(False, "b")) is None
    assert len(recorder.bundles()) == 2
    # a deferral cancelled by recovery does not fire later
    recorder2 = IncidentRecorder(cooldown_s=0.15)
    recorder2.observe(_verdict(False, "a"))
    recorder2.observe(_verdict(True))
    recorder2.observe(_verdict(False, "b"))  # deferred
    recorder2.observe(_verdict(True))        # recovered: cancel
    time.sleep(0.2)
    assert recorder2.observe(_verdict(True)) is None
    assert len(recorder2.bundles()) == 1


def test_bundles_persist_to_dir_with_bounded_retention(tmp_path):
    incidents_dir = tmp_path / "incidents"
    recorder = IncidentRecorder(capacity=2, cooldown_s=0.0,
                                dir=str(incidents_dir))
    for i in range(4):
        recorder.capture(_verdict(False, f"r{i}"), trigger="manual")
    files = sorted(p.name for p in incidents_dir.glob("inc-*.json"))
    assert len(files) == 2  # oldest pruned past capacity
    assert files == ["inc-000003.json", "inc-000004.json"]
    with open(incidents_dir / files[-1]) as f:
        assert json.load(f)["reasons"] == ["r3"]
    assert len(recorder.bundles()) == 2


def test_incident_ids_resume_after_restart(tmp_path):
    """A restarted process must not recycle ids and os.replace a crashed
    run's persisted bundle — the evidence the dir exists to preserve."""
    incidents_dir = str(tmp_path / "incidents")
    first = IncidentRecorder(cooldown_s=0.0, dir=incidents_dir)
    first.capture(_verdict(False, "crash-era"), trigger="manual")
    # "restart": a fresh recorder over the same directory
    second = IncidentRecorder(cooldown_s=0.0, dir=incidents_dir)
    bundle = second.capture(_verdict(False, "post-boot"), trigger="manual")
    assert bundle["id"] == "inc-000002"
    with open(tmp_path / "incidents" / "inc-000001.json") as f:
        assert json.load(f)["reasons"] == ["crash-era"]  # survived


def test_concurrent_observers_capture_once():
    """The REST handler, the health-watch loop, and the scheduler can
    all report the same degraded verdict concurrently — one bundle."""
    recorder = IncidentRecorder(cooldown_s=3600.0)
    barrier = threading.Barrier(6)

    def probe():
        barrier.wait()
        recorder.observe(_verdict(False, "device-degraded"))

    threads = [threading.Thread(target=probe) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(recorder.bundles()) == 1


# ------------------------------------------------- end-to-end drill (REST)


def _drill_rig():
    from cook_tpu.obs.telemetry import DeviceTelemetry
    from cook_tpu.rest.api import ApiConfig, CookApi
    from cook_tpu.rest.server import ServerThread

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "drill",
        [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=8000, cpus=16)
         for i in range(3)],
        clock=clock)
    scheduler = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(chunk=0, device_fallback_cycles=3,
                          device_latency_guard=3.0),
        incident_cooldown_s=0.0))
    # tight latency windows so the guard arms after a handful of cycles
    # instead of the production 12-sample warmup; the wide rel_floor
    # (5x baseline) keeps host-scheduling jitter on millisecond solves
    # from tripping the band before the 100x injected delay does
    scheduler.telemetry = DeviceTelemetry(
        latency_window=16, latency_recent=2, latency_min_samples=3,
        latency_rel_floor=5.0, update_memory_gauges=False)
    scheduler.telemetry.health_observer = scheduler.incidents.observe
    # injected profiler: the drill proves the auto-capture WIRING, not
    # jax's profiler
    fake = FakeProfiler()
    scheduler.profiler = ProfileCapturer(
        base_dir="/tmp/unused", start_fn=fake.start, stop_fn=fake.stop,
        default_duration_s=0.01, cooldown_s=0.0)
    scheduler.incidents.profiler = scheduler.profiler
    scheduler.incidents.auto_profile = True
    api = CookApi(store, scheduler, ApiConfig())
    server = ServerThread(api).start()
    return clock, store, cluster, scheduler, api, server, fake


def _cycle(scheduler, store, clock, n_jobs=2, prefix="d"):
    uuid_base = f"{prefix}-{clock.now_ms}"
    store.submit_jobs([
        Job(uuid=f"{uuid_base}-{i}", user=f"u{i % 2}", pool="default",
            command="true", resources=Resources(mem=100, cpus=0.5),
            max_retries=5)
        for i in range(n_jobs)])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    clock.advance(1000)


def test_end_to_end_drill_latency_fault_to_bundle_to_recovery():
    """The acceptance drill: device.solve latency armed -> health
    degrades -> ONE bundle auto-captured (verdict + contention + cycle
    records + chrome trace + auto profile) -> health recovers ->
    /debug/incidents lists exactly one bundle, recovery-stamped."""
    import urllib.request

    clock, store, cluster, scheduler, api, server, fake = _drill_rig()

    def get(path):
        req = urllib.request.Request(
            server.url + path,
            headers={"X-Cook-Requesting-User": "admin"})
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    try:
        for _ in range(6):  # warm the latency baseline
            _cycle(scheduler, store, clock)
        assert get("/debug/health")["status"] == "ok"

        faults.arm(faults.FaultSchedule([faults.FaultRule(
            point=faults.DEVICE_SOLVE, mode="delay", delay_s=0.25)]))
        for _ in range(4):  # slow solves push the recent median past
            _cycle(scheduler, store, clock)  # guard x baseline
        health = get("/debug/health")
        assert health["status"] == "degraded"
        reasons = set(health["reasons"])
        assert reasons & {"device-degraded", "solve-latency-regression"}, \
            reasons

        index = get("/debug/incidents")
        assert len(index["incidents"]) == 1
        bundle = get(f"/debug/incidents/{index['incidents'][0]['id']}")
        assert bundle["trigger"] == "health-transition"
        assert bundle["verdict"]["status"] == "degraded"
        assert "store_lock" in bundle["contention"]  # contention snapshot
        assert bundle["cycles"], "bundle carries no cycle records"
        assert bundle["trace"]["traceEvents"] is not None
        assert bundle["profile"]["started"] is True
        assert fake.started, "auto profile never reached the profiler"

        faults.disarm()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            _cycle(scheduler, store, clock)
            if get("/debug/health")["status"] == "ok":
                break
        assert get("/debug/health")["status"] == "ok"

        index = get("/debug/incidents")
        assert len(index["incidents"]) == 1  # still exactly one
        assert index["incidents"][0]["recovered_time"] is not None
    finally:
        faults.disarm()
        server.stop()


def test_contention_only_degradation_is_not_a_flap():
    """A verdict degraded ONLY by contention must not oscillate through
    the device-side observer: repeated /debug/health probes capture one
    bundle, not one per probe."""
    clock, store, cluster, scheduler, api, server, fake = _drill_rig()
    try:
        _cycle(scheduler, store, clock)
        degraded = [{"reason": "fsync-stall", "detail": "test"}]
        api.contention.evaluate = lambda: (degraded, {})
        for _ in range(4):
            verdict = api.health_verdict()
            assert verdict["status"] == "degraded"
        assert len(scheduler.incidents.bundles()) == 1
    finally:
        server.stop()


# ----------------------------------------------------------- job timeline


def test_timeline_reconstructs_preempted_lifecycle():
    """Acceptance: submit -> ranked/skipped -> matched -> running ->
    preempted -> re-queued -> matched again, with per-cycle skip/wait
    attribution and rank/DRU stamps."""
    from cook_tpu.models.entities import Share

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "tl",
        [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=1000, cpus=4)
         for i in range(2)],
        clock=clock, default_runtime_ms=60_000)
    scheduler = Scheduler(store, [cluster],
                          SchedulerConfig(match=MatchConfig(chunk=0)))
    pool = store.pools["default"]
    # bob's share dwarfs alice's, so bob's jobs always outrank hers
    store.set_share(Share(user="bob", pool="default",
                          resources=Resources(mem=1_000_000, cpus=1000)))
    store.set_share(Share(user="alice", pool="default",
                          resources=Resources(mem=100, cpus=1)))

    def cycle():
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
        clock.advance(1000)

    job_a = Job(uuid="tl-a", user="alice", pool="default", command="true",
                resources=Resources(mem=800, cpus=1), max_retries=5)
    store.submit_jobs([job_a])
    cycle()
    assert store.jobs["tl-a"].state is JobState.RUNNING
    [task_a] = store.jobs["tl-a"].instance_ids
    host_a = store.instances[task_a].hostname
    store.submit_jobs([Job(uuid="tl-b1", user="bob", pool="default",
                           command="true",
                           resources=Resources(mem=800, cpus=1),
                           max_retries=5)])
    cycle()  # bob's first job takes the other host
    assert store.jobs["tl-b1"].state is JobState.RUNNING

    # the rebalancer's preemption effect (_transact_preemption):
    # instance fails with the mea-culpa preemption reason, the backend
    # task is killed (freeing the host), the job re-queues
    store.update_instance_state(task_a, InstanceStatus.FAILED,
                                "preempted-by-rebalancer")
    cluster.safe_kill_task(task_a)
    assert store.jobs["tl-a"].state is JobState.WAITING

    # bob's second job outranks tl-a and takes the freed host; tl-a
    # cannot return to the host it failed on (novel-host constraint) and
    # nothing else fits: insufficient-resources for a few cycles
    store.submit_jobs([Job(uuid="tl-b2", user="bob", pool="default",
                           command="true",
                           resources=Resources(mem=800, cpus=1),
                           max_retries=5)])
    for _ in range(3):
        cycle()
    assert store.jobs["tl-a"].state is JobState.WAITING
    assert store.jobs["tl-b2"].state is JobState.RUNNING

    # bob's jobs complete; tl-a matches again on a novel host
    clock.advance(61_000)
    cluster.advance_to(clock.now_ms)
    cycle()
    assert store.jobs["tl-a"].state is JobState.RUNNING
    assert store.instances[store.jobs["tl-a"].instance_ids[-1]].hostname \
        != host_a

    timeline = job_timeline(store, scheduler.recorder,
                            store.jobs["tl-a"])
    kinds = [e["kind"] for e in timeline["events"]]
    for expected in ("submitted", "matched", "launched", "preempted",
                     "re-queued", "waiting"):
        assert expected in kinds, (expected, kinds)
    # causal order: submit < first match < preemption < re-queue <
    # waiting attribution < second match
    assert kinds.index("submitted") < kinds.index("matched")
    assert kinds.index("preempted") < kinds.index("re-queued")
    assert kinds.index("re-queued") < kinds.index("waiting")
    assert kinds.count("matched") == 2
    assert kinds.count("launched") == 2

    [preempted] = [e for e in timeline["events"]
                   if e["kind"] == "preempted"]
    assert preempted["reason"] == "preempted-by-rebalancer"
    assert preempted["mea_culpa"] is True

    waiting_events = [e for e in timeline["events"]
                      if e["kind"] == "waiting"]
    attribution = timeline["waiting"]["cycles_by_reason"]
    assert attribution.get("insufficient-resources", 0) >= 3
    [skip_run] = [e for e in waiting_events
                  if e["code"] == "insufficient-resources"]
    assert skip_run["cycles"] >= 3
    assert "cycles skipped: insufficient-resources" in skip_run["summary"]
    assert "last_rank" in skip_run and "last_dru" in skip_run

    matched = [e for e in timeline["events"] if e["kind"] == "matched"]
    assert all("rank" in e and "host" in e for e in matched)
    assert timeline["phases"]["submit_to_first_match_ms"] == 0
    assert timeline["state"] == "running"
    assert timeline["instances"] == 2
    # the re-queue is timestamped at ITS attempt's death, not the
    # (re-stamped) latest waiting start
    [requeued] = [e for e in timeline["events"] if e["kind"] == "re-queued"]
    assert requeued["t_ms"] == \
        store.instances[task_a].end_time_ms

    # once the job COMPLETES, the historical re-queue must survive in
    # the timeline (it happened), and no phantom re-queue is added for
    # the successful final attempt
    clock.advance(61_000)
    cluster.advance_to(clock.now_ms)
    assert store.jobs["tl-a"].state is JobState.COMPLETED
    done = job_timeline(store, scheduler.recorder, store.jobs["tl-a"])
    done_kinds = [e["kind"] for e in done["events"]]
    assert done_kinds.count("re-queued") == 1
    assert "completed" in done_kinds


def test_timeline_rest_endpoint_and_cycles_since_filter():
    """GET /jobs/{uuid}/timeline serves the reconstruction; /debug/cycles
    ?since= slices the ring incrementally."""
    import urllib.request

    from cook_tpu.rest.api import ApiConfig, CookApi
    from cook_tpu.rest.server import ServerThread

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "tl2", [MockHost(node_id="h0", hostname="h0", mem=4000, cpus=8)],
        clock=clock)
    scheduler = Scheduler(store, [cluster],
                          SchedulerConfig(match=MatchConfig(chunk=0)))
    store.submit_jobs([Job(uuid="tl2-a", user="u", pool="default",
                           command="true",
                           resources=Resources(mem=100, cpus=1))])
    pool = store.pools["default"]
    for _ in range(3):
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
        clock.advance(1000)
    api = CookApi(store, scheduler, ApiConfig())
    server = ServerThread(api).start()

    def get(path, expect=200):
        req = urllib.request.Request(
            server.url + path,
            headers={"X-Cook-Requesting-User": "admin"})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == expect
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            assert e.code == expect
            return None

    try:
        timeline = get("/jobs/tl2-a/timeline")
        assert timeline["uuid"] == "tl2-a"
        assert timeline["events"][0]["kind"] == "submitted"
        assert any(e["kind"] == "matched" for e in timeline["events"])
        get("/jobs/no-such-job/timeline", expect=404)

        all_cycles = get("/debug/cycles?limit=100")["cycles"]
        assert len(all_cycles) == 3
        newest = get(f"/debug/cycles?since={all_cycles[-2]['cycle']}")
        assert [c["cycle"] for c in newest["cycles"]] == \
            [all_cycles[-1]["cycle"]]
        assert get("/debug/cycles?since=999999")["cycles"] == []
    finally:
        server.stop()


# -------------------------------------------------- recorder job history


def test_job_history_is_bounded_and_ordered():
    from cook_tpu.scheduler.flight_recorder import FlightRecorder

    recorder = FlightRecorder(history_per_job=4)
    for i in range(10):
        builder = recorder.begin("default", t_ms=i * 1000)
        builder.note_skip("job-x", "insufficient-resources")
        recorder.commit(builder)
    history = recorder.job_history("job-x")
    assert len(history) == 4  # bounded per job
    cycles = [e["cycle"] for e in history]
    assert cycles == sorted(cycles)  # chronological
    assert cycles[-1] == 10
    assert all(e["t_ms"] == (e["cycle"] - 1) * 1000 for e in history)
    assert recorder.job_history("never-seen") == []


def test_job_history_lru_bounds_job_count():
    from cook_tpu.scheduler.flight_recorder import FlightRecorder

    recorder = FlightRecorder(job_reason_capacity=5)
    builder = recorder.begin("default", t_ms=0)
    for i in range(20):
        builder.note_skip(f"job-{i}", "no-offers")
    recorder.commit(builder)
    tracked = sum(1 for i in range(20)
                  if recorder.job_history(f"job-{i}"))
    assert tracked == 5  # LRU over jobs, oldest evicted
    assert recorder.job_history("job-19")  # newest survives
