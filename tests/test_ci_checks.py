"""tools/ci_checks.py: one entry point for lint + smoke bench + gate."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))

import ci_checks  # noqa: E402

REPO_ROOT = str(pathlib.Path(__file__).parent.parent)


def run(argv, calls=None, codes=None):
    """Drive main() with stubbed steps; record invocation order."""
    calls = [] if calls is None else calls
    codes = codes or {}

    def step(name):
        def fn():
            calls.append(name)
            return codes.get(name, 0)
        return fn

    steps = {name: step(name)
             for name in ("lint_metrics", "smoke_bench", "bench_gate",
                          "chaos_smoke", "debug_smoke")}
    return ci_checks.main(argv, steps=steps), calls


def test_runs_all_steps_in_order_and_passes():
    code, calls = run(["--root", REPO_ROOT])
    assert code == 0
    assert calls == ["lint_metrics", "smoke_bench", "bench_gate",
                     "chaos_smoke", "debug_smoke"]


def test_skip_bench_runs_lint_only():
    code, calls = run(["--root", REPO_ROOT, "--skip-bench"])
    assert code == 0
    assert calls == ["lint_metrics"]


def test_failure_does_not_mask_later_steps():
    code, calls = run(["--root", REPO_ROOT],
                      codes={"lint_metrics": 1})
    assert code == 1
    # later steps still ran (one verdict, every step's result reported)
    assert calls == ["lint_metrics", "smoke_bench", "bench_gate",
                     "chaos_smoke", "debug_smoke"]


def test_gate_failure_fails_the_pipeline():
    code, calls = run(["--root", REPO_ROOT], codes={"bench_gate": 1})
    assert code == 1


def test_step_exception_counts_as_failure():
    def boom():
        raise RuntimeError("accelerator on fire")

    steps = {"lint_metrics": boom,
             "smoke_bench": lambda: 0,
             "bench_gate": lambda: 0,
             "chaos_smoke": lambda: 0,
             "debug_smoke": lambda: 0}
    assert ci_checks.main(["--root", REPO_ROOT], steps=steps) == 1


def test_real_lint_step_runs_clean_on_this_repo():
    """The wired lint target actually lints this tree (the smoke/gate
    steps pay a real bench run and are covered by test_bench_smoke)."""
    assert ci_checks.run_lint(REPO_ROOT) == 0
