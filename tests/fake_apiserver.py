"""In-process fake Kubernetes apiserver speaking real HTTP.

Test double for `HttpKubeApi` (cook_tpu/cluster/k8s_http.py) with faithful
watch semantics: LIST returns a resourceVersion; WATCH streams JSON-line
events from an event buffer starting after the requested resourceVersion;
`inject_gap()` compacts the buffer so resumed watches get 410 Gone and the
client must re-list — the failure mode the reference recovers from in
initialize-pod-watch (api.clj:449)."""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit


class FakeApiServerState:
    def __init__(self):
        self.lock = threading.Condition()
        self.rv = 100
        self.nodes: dict[str, dict] = {}
        self.pods: dict[str, dict] = {}
        # (rv, type, manifest-snapshot); compacted by inject_gap()
        self.events: list[tuple[int, str, dict]] = []
        self.min_event_rv = 0
        self.auth_headers: list[str] = []
        self.watch_epoch = 0

    # ------------------------------------------------------- mutations

    def add_node(self, name: str, mem_mb: float, cpus: float,
                 labels: dict | None = None) -> None:
        with self.lock:
            self.nodes[name] = {
                "metadata": {"name": name, "labels": labels or {}},
                "spec": {},
                "status": {
                    "allocatable": {"memory": f"{int(mem_mb)}Mi",
                                    "cpu": str(cpus)},
                    "conditions": [{"type": "Ready", "status": "True"}],
                },
            }

    def create_pod(self, manifest: dict) -> None:
        with self.lock:
            name = manifest["metadata"]["name"]
            if name in self.pods:
                raise KeyError(name)
            manifest.setdefault("status", {})["phase"] = "Pending"
            self.rv += 1
            manifest["metadata"]["resourceVersion"] = str(self.rv)
            self.pods[name] = manifest
            self.events.append((self.rv, "ADDED", json.loads(json.dumps(manifest))))
            self.lock.notify_all()

    def delete_pod(self, name: str) -> bool:
        with self.lock:
            manifest = self.pods.pop(name, None)
            if manifest is None:
                return False
            self.rv += 1
            self.events.append((self.rv, "DELETED",
                                json.loads(json.dumps(manifest))))
            self.lock.notify_all()
            return True

    def set_phase(self, name: str, phase: str, *, reason: str = "") -> None:
        with self.lock:
            manifest = self.pods[name]
            manifest["status"]["phase"] = phase
            if reason:
                manifest["status"]["reason"] = reason
            self.rv += 1
            manifest["metadata"]["resourceVersion"] = str(self.rv)
            self.events.append((self.rv, "MODIFIED",
                                json.loads(json.dumps(manifest))))
            self.lock.notify_all()

    def inject_gap(self) -> None:
        """Compact the event history and sever live watches: resumed
        watches with a pre-compaction resourceVersion now get 410."""
        with self.lock:
            self.events.clear()
            self.min_event_rv = self.rv + 1
            self.watch_epoch += 1
            self.lock.notify_all()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    state: FakeApiServerState  # set by make_server

    def log_message(self, *args):  # quiet
        pass

    def _json(self, code: int, body: dict) -> None:
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        st = self.state
        st.auth_headers.append(self.headers.get("Authorization", ""))
        parts = urlsplit(self.path)
        query = {k: v[0] for k, v in parse_qs(parts.query).items()}
        if parts.path == "/api/v1/nodes":
            with st.lock:
                items = list(st.nodes.values())
            return self._json(200, {"items": items})
        if parts.path.endswith("/pods") and query.get("watch") != "1":
            with st.lock:
                items = json.loads(json.dumps(list(st.pods.values())))
                rv = str(st.rv)
            return self._json(200, {"items": items,
                                    "metadata": {"resourceVersion": rv}})
        if parts.path.endswith("/pods"):
            return self._watch(query)
        return self._json(404, {"message": "not found"})

    def _watch(self, query: dict) -> None:
        st = self.state
        from_rv = int(query.get("resourceVersion") or 0)
        timeout_s = float(query.get("timeoutSeconds", 30))
        with st.lock:
            if from_rv < st.min_event_rv - 1 and st.min_event_rv:
                return self._json(410, {"kind": "Status", "code": 410,
                                        "reason": "Expired"})
            epoch = st.watch_epoch
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Connection", "close")
        self.end_headers()
        import time

        deadline = time.time() + timeout_s
        sent_rv = from_rv
        while True:
            with st.lock:
                if st.watch_epoch != epoch:
                    return  # severed: client must reconnect (and may 410)
                batch = [e for e in st.events if e[0] > sent_rv]
                if not batch:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        return
                    st.lock.wait(timeout=min(remaining, 0.2))
                    continue
            for rv, etype, manifest in batch:
                line = json.dumps({"type": etype, "object": manifest}) + "\n"
                try:
                    self.wfile.write(line.encode())
                    self.wfile.flush()
                except OSError:
                    return
                sent_rv = rv

    def do_POST(self):
        st = self.state
        st.auth_headers.append(self.headers.get("Authorization", ""))
        length = int(self.headers.get("Content-Length", 0))
        manifest = json.loads(self.rfile.read(length))
        try:
            st.create_pod(manifest)
        except KeyError:
            return self._json(409, {"message": "AlreadyExists"})
        return self._json(201, manifest)

    def do_DELETE(self):
        st = self.state
        st.auth_headers.append(self.headers.get("Authorization", ""))
        name = urlsplit(self.path).path.rsplit("/", 1)[-1]
        if st.delete_pod(name):
            return self._json(200, {"status": "Success"})
        return self._json(404, {"message": "NotFound"})


def make_server() -> tuple[ThreadingHTTPServer, FakeApiServerState, str]:
    """Start a fake apiserver on an ephemeral port; returns (server,
    state, base_url).  Caller must server.shutdown()."""
    state = FakeApiServerState()
    handler = type("BoundHandler", (_Handler,), {"state": state})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    host, port = server.server_address
    return server, state, f"http://{host}:{port}"
