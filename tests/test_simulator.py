"""End-to-end simulator tests (the role of zz_simulator.clj): trace replay
through the real scheduler + mock cluster, determinism, fairness, and
preemption behavior."""
import numpy as np

from cook_tpu.models.entities import JobState
from cook_tpu.scheduler.core import SchedulerConfig
from cook_tpu.scheduler.matcher import MatchConfig
from cook_tpu.scheduler.rebalancer import RebalancerParams
from cook_tpu.sim.simulator import (
    SimConfig,
    Simulator,
    TraceHost,
    TraceJob,
    synth_trace,
)


def small_trace():
    jobs, hosts = synth_trace(60, 8, n_users=5, seed=42,
                              mean_runtime_ms=60_000,
                              submit_span_ms=120_000)
    return jobs, hosts


def test_simulator_completes_all_jobs():
    jobs, hosts = small_trace()
    sim = Simulator(jobs, hosts, SimConfig(cycle_ms=15_000, max_cycles=500))
    result = sim.run()
    statuses = {r["status"] for r in result.rows}
    assert all(
        sim.store.jobs[j.uuid].state == JobState.COMPLETED for j in jobs
    ), statuses
    # every job ran exactly once (no retries needed in a healthy cluster)
    started = [r for r in result.rows if r["task_id"]]
    assert len(started) == len(jobs)


def test_simulator_retains_metrics_history_on_virtual_clock():
    """history_every > 0: a long run retains the same multi-resolution
    series a live node's sampler would, timestamped in VIRTUAL seconds
    (obs/tsdb.py; `sim run --history-every N --history-out FILE`)."""
    jobs, hosts = small_trace()
    cfg = SimConfig(cycle_ms=15_000, max_cycles=500, history_every=2)
    result = Simulator(jobs, hosts, cfg).run()
    raw = result.metrics_history["raw"]["series"]
    assert raw, "history_every set but no series retained"
    queue_series = [k for k in raw if k.startswith("rank.queue_len")]
    assert queue_series, sorted(raw)[:10]
    points = raw[queue_series[0]]
    # virtual-clock timestamps: monotone, bounded by the simulated span
    times = [t for t, _ in points]
    assert times == sorted(times)
    assert times[-1] <= result.virtual_ms / 1000.0
    # the 10m rollup rides along (one simulated cycle is 15 virtual
    # seconds, so a multi-minute run folds into rollup buckets)
    rolled = result.metrics_history["10m"]["series"][queue_series[0]]
    assert sum(b["count"] for b in rolled) == len(points)
    # off by default: no retained history, no cost
    assert Simulator(*small_trace(), SimConfig(
        cycle_ms=15_000, max_cycles=50)).run().metrics_history == {}


def test_simulator_determinism():
    jobs, hosts = small_trace()
    r1 = Simulator(jobs, hosts, SimConfig(cycle_ms=15_000)).run()
    r2 = Simulator(jobs, hosts, SimConfig(cycle_ms=15_000)).run()
    t1 = [(r["job_uuid"], r["start_ms"], r["host"], r["status"]) for r in r1.rows]
    t2 = [(r["job_uuid"], r["start_ms"], r["host"], r["status"]) for r in r2.rows]
    assert t1 == t2


def test_simulator_respects_capacity():
    # 4 hosts x 4 cpus; jobs need 2 cpus => max 8 concurrent
    jobs = [
        TraceJob(uuid=f"j{i}", user="u", submit_time_ms=0, runtime_ms=50_000,
                 mem=100, cpus=2)
        for i in range(20)
    ]
    hosts = [
        TraceHost(node_id=f"n{i}", hostname=f"n{i}", mem=1000, cpus=4)
        for i in range(4)
    ]
    sim = Simulator(jobs, hosts, SimConfig(cycle_ms=10_000))
    result = sim.run()
    # at no virtual instant can more than 8 tasks overlap
    events = []
    for r in result.rows:
        if r["start_ms"] is not None and r["status"] == "success":
            events.append((r["start_ms"], 1))
            events.append((r["end_ms"], -1))
    events.sort()
    live = peak = 0
    for _, delta in events:
        live += delta
        peak = max(peak, live)
    assert peak <= 8
    assert all(sim.store.jobs[j.uuid].state == JobState.COMPLETED for j in jobs)


def test_fair_share_ordering():
    """A light user's job should schedule ahead of a heavy user's backlog
    when capacity frees up (DRU fairness)."""
    jobs = []
    # heavy user floods at t=0
    for i in range(16):
        jobs.append(TraceJob(uuid=f"h{i}", user="heavy", submit_time_ms=0,
                             runtime_ms=200_000, mem=100, cpus=2))
    # light user submits one job a bit later
    jobs.append(TraceJob(uuid="light-job", user="light",
                         submit_time_ms=20_000, runtime_ms=30_000,
                         mem=100, cpus=2))
    hosts = [TraceHost(node_id=f"n{i}", hostname=f"n{i}", mem=1000, cpus=4)
             for i in range(2)]  # only 4 concurrent slots
    sim = Simulator(jobs, hosts, SimConfig(cycle_ms=10_000, max_cycles=300))
    sim.run()
    # the light job must start before the heavy user's queue drains
    light_insts = sim.store.job_instances("light-job")
    assert light_insts, "light job never ran"
    light_start = light_insts[0].start_time_ms
    heavy_starts = sorted(
        inst.start_time_ms
        for i in range(16)
        for inst in sim.store.job_instances(f"h{i}")
    )
    # light job starts before at least 8 of the heavy jobs
    assert sum(1 for s in heavy_starts if s > light_start) >= 8


def test_preemption_frees_room_for_starved_user():
    """With the rebalancer on, a starved user's job preempts the hog's tasks
    (reference rebalancer semantics: dru over threshold + min diff)."""
    jobs = [
        TraceJob(uuid=f"hog{i}", user="hog", submit_time_ms=0,
                 runtime_ms=10_000_000, mem=400, cpus=4)
        for i in range(4)
    ] + [
        TraceJob(uuid="starved", user="starved", submit_time_ms=30_000,
                 runtime_ms=20_000, mem=400, cpus=4),
    ]
    hosts = [TraceHost(node_id=f"n{i}", hostname=f"n{i}", mem=800, cpus=8)
             for i in range(2)]  # hog fills everything
    cfg = SimConfig(
        cycle_ms=10_000,
        rebalance_every=2,
        max_cycles=60,
        scheduler=SchedulerConfig(
            rebalancer=RebalancerParams(
                safe_dru_threshold=0.0, min_dru_diff=0.1, max_preemption=10
            )
        ),
    )
    # shares make the drus comparable
    sim = Simulator(jobs, hosts, cfg)
    from cook_tpu.models.entities import DEFAULT_USER, Resources, Share

    sim.store.set_share(Share(user=DEFAULT_USER, pool="default",
                              resources=Resources(mem=800, cpus=8, gpus=1)))
    sim.run()
    starved = sim.store.jobs["starved"]
    assert starved.state == JobState.COMPLETED
    # at least one hog task was preempted mea-culpa and retried
    preempted = [
        inst
        for i in range(4)
        for inst in sim.store.job_instances(f"hog{i}")
        if inst.reason_code == 1002
    ]
    assert preempted
