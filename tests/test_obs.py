"""cook_tpu/obs/: compile observatory, rolling baselines, quality
monitor, device-memory probe, and the DeviceTelemetry facade."""
import numpy as np

from cook_tpu.obs import (
    CompileObservatory,
    DeviceTelemetry,
    RollingBaseline,
    QualityMonitor,
    device_memory_stats,
    update_device_memory_gauges,
)
from cook_tpu.obs.compile_observatory import shape_signature
from cook_tpu.ops.common import bucket_size, fetch_result
from cook_tpu.utils.metrics import global_registry


class TestCompileObservatory:
    def test_first_seen_key_is_a_compile(self):
        obs = CompileObservatory()
        assert obs.observe_solve("match", (1024, 128), "xla")
        assert not obs.observe_solve("match", (1024, 128), "xla")
        # a new shape, backend, or op each compile fresh programs
        assert obs.observe_solve("match", (2048, 128), "xla")
        assert obs.observe_solve("match", (1024, 128), "bucketed")
        assert obs.observe_solve("rank", (1024, 128), "xla")

    def test_shape_signature(self):
        assert shape_signature((131072, 16384)) == "131072x16384"
        assert shape_signature((64,)) == "64"

    def test_storm_from_padding_bucket_churn(self):
        """The acceptance scenario: a queue oscillating across padding
        buckets mints a new padded shape almost every solve."""
        obs = CompileObservatory(window=8, storm_threshold=3,
                                 warmup_solves=0)
        churn = [100, 1100, 2100, 4100, 100, 1100]  # queue length per cycle
        for n in churn:
            obs.observe_solve("match", (bucket_size(n), 2048), "xla")
        storms = obs.storming_ops()
        assert "match" in storms
        assert storms["match"]["compiles_in_window"] >= 3
        # counted, not just flagged
        stats = obs.stats()["match"]
        assert stats["programs"] == 4  # 128, 2048, 4096, 8192 buckets
        assert stats["storming"]

    def test_stable_shapes_never_storm_and_storms_clear(self):
        obs = CompileObservatory(window=8, storm_threshold=3,
                                 warmup_solves=0)
        for n in [100, 1100, 2100, 4100]:
            obs.observe_solve("match", (bucket_size(n), 2048), "xla")
        assert "match" in obs.storming_ops()
        # a full window of warm same-shape solves drains the storm
        for _ in range(8):
            obs.observe_solve("match", (128, 2048), "xla")
        assert obs.storming_ops() == {}

    def test_first_boot_warmup_never_storms(self):
        """A fresh process compiles every pool's shape once by
        construction; that must not page recompile-storm on each deploy.
        Churn AFTER warmup still triggers."""
        obs = CompileObservatory(window=8, storm_threshold=3)  # warmup=8
        for i in range(6):  # boot: 6 distinct pool shapes, all compile
            obs.observe_solve("match", (64 * (i + 1), 2048), "xla")
        assert obs.storming_ops() == {}
        for _ in range(4):  # steady state
            obs.observe_solve("match", (64, 2048), "xla")
        for i in range(4):  # post-warmup padding churn: a real storm
            obs.observe_solve("match", (1 << (14 + i), 2048), "xla")
        assert "match" in obs.storming_ops()
        # compile COUNTS were honest throughout (warmup included)
        assert obs.stats()["match"]["programs"] == 10

    def test_per_key_compile_counts_exported(self):
        # the counters are process-global across observatories: assert
        # deltas, not absolutes (other suites run match solves too)
        counter = global_registry.counter("obs.compile.count")
        solves = global_registry.counter("obs.solve.count")
        key = {"op": "match", "shape": "1024x256", "backend": "xla"}
        skey = {"op": "match", "backend": "xla"}
        c0, s0 = counter.value(key), solves.value(skey)
        obs = CompileObservatory()
        obs.observe_solve("match", (1024, 256), "xla")
        obs.observe_solve("match", (1024, 256), "xla")
        assert counter.value(key) == c0 + 1.0  # one compile, two solves
        assert solves.value(skey) == s0 + 2.0


class TestRollingBaseline:
    def test_too_few_samples_returns_none(self):
        b = RollingBaseline(window=16, recent=4, min_samples=8)
        for _ in range(7):
            b.add(1.0)
        assert b.snapshot() is None

    def test_flat_series_is_calm(self):
        b = RollingBaseline(window=16, recent=4, min_samples=8)
        for _ in range(16):
            b.add(1.0)
        snap = b.snapshot()
        assert snap["deviation"] == 0.0
        assert b.anomaly_high() is None and b.anomaly_low() is None

    def test_rise_flags_high_not_low(self):
        b = RollingBaseline(window=32, recent=4, min_samples=8)
        for _ in range(20):
            b.add(0.010)
        for _ in range(4):
            b.add(0.100)
        assert b.anomaly_high() is not None
        assert b.anomaly_low() is None

    def test_drop_flags_low(self):
        b = RollingBaseline(window=32, recent=4, min_samples=8)
        for _ in range(20):
            b.add(1.0)
        for _ in range(4):
            b.add(0.8)
        anomaly = b.anomaly_low()
        assert anomaly is not None and anomaly["deviation"] < 0

    def test_rel_floor_absorbs_noise(self):
        b = RollingBaseline(window=32, recent=4, min_samples=8,
                            rel_floor=0.10)
        for _ in range(20):
            b.add(1.0)
        for _ in range(4):
            b.add(0.95)  # -5%: inside the 10% floor band
        assert b.anomaly_low() is None


class TestQualityMonitor:
    def test_sampling_cadence(self):
        q = QualityMonitor(sample_every=3)
        due = [q.due("p") for _ in range(6)]
        assert due == [False, False, True, False, False, True]
        assert not any(QualityMonitor(sample_every=0).due("p")
                       for _ in range(5))

    def test_floor_breach_is_drift(self):
        q = QualityMonitor(sample_every=1, floor=0.97)
        q.record_sample("default", 0.90)
        drift = q.drifting_pools()
        assert drift["default"]["kind"] == "parity-floor"

    def test_rolling_drop_is_drift_and_recovers(self):
        q = QualityMonitor(sample_every=1, floor=0.5)  # floor out of play
        for _ in range(12):
            q.record_sample("default", 1.0)
        assert q.drifting_pools() == {}
        for _ in range(4):
            q.record_sample("default", 0.90)
        assert q.drifting_pools()["default"]["kind"] == "rolling-baseline"
        for _ in range(8):
            q.record_sample("default", 1.0)
        assert q.drifting_pools() == {}

    def test_drift_events_are_edge_triggered(self):
        counter = global_registry.counter("obs.quality.drift_events")
        before = counter.value({"pool": "edge"})
        q = QualityMonitor(sample_every=1, floor=0.97)
        for _ in range(5):
            q.record_sample("edge", 0.80)  # one sustained episode
        assert counter.value({"pool": "edge"}) == before + 1
        q.record_sample("edge", 1.0)  # recover (floor ok, above band? no
        # — band check needs min_samples; floor check clears)
        for _ in range(2):
            q.record_sample("edge", 0.80)  # second episode
        assert counter.value({"pool": "edge"}) == before + 2

    def test_shadow_solve_against_reference(self):
        """A device assignment identical to the reference scores 1.0; an
        empty one scores 0."""
        import jax.numpy as jnp

        from cook_tpu.ops import cpu_reference as ref
        from cook_tpu.scheduler.matcher import PreparedPool

        rng = np.random.default_rng(0)
        j, n = 32, 8
        demands = np.stack([rng.uniform(100, 1000, j),
                            rng.uniform(0.5, 4, j),
                            np.zeros(j), np.zeros(j)], axis=-1
                           ).astype(np.float32)
        totals = np.stack([np.full(n, 4000.0), np.full(n, 16.0)],
                          axis=-1).astype(np.float32)
        avail = np.concatenate([totals, np.zeros((n, 2), np.float32)],
                               axis=-1)
        ref_assign = ref.np_greedy_match(demands, avail, totals)

        class Nodes:
            pass

        nodes = Nodes()
        nodes.n = n
        prepared = PreparedPool(pool=None, outcome=None)
        prepared.considerable = list(range(j))
        prepared.nodes = nodes
        prepared.problem = type("P", (), {})()
        prepared.problem.demands = jnp.asarray(demands)
        prepared.problem.avail = jnp.asarray(avail)
        prepared.problem.totals = jnp.asarray(totals)
        prepared.feasible = None

        q = QualityMonitor(sample_every=1)
        assert q.shadow_solve(prepared, ref_assign, "p1") == 1.0
        none_placed = np.full(j, -1)
        assert q.shadow_solve(prepared, none_placed, "p1") == 0.0


class TestDeviceMonitor:
    def test_unobservable_returns_none(self):
        # CPU devices expose no allocator stats; must degrade, not lie
        assert update_device_memory_gauges(lambda: None) is None

    def test_fake_device_stats(self):
        class Dev:
            def memory_stats(self):
                return {"bytes_in_use": 600, "bytes_limit": 1000}

        stats = device_memory_stats(Dev())
        assert stats["utilization"] == 0.6
        out = update_device_memory_gauges(lambda: stats)
        assert out["bytes_in_use"] == 600
        g = global_registry.gauge("obs.device.mem_utilization")
        assert g.value() == 0.6

    def test_raising_provider_degrades(self):
        class Broken:
            def memory_stats(self):
                raise RuntimeError("tunnel wedged")

        assert device_memory_stats(Broken()) is None


class TestDeviceTelemetry:
    def make(self, **kw):
        kw.setdefault("memory_stats_fn", lambda: None)
        return DeviceTelemetry(**kw)

    def test_last_solve_snapshot(self):
        t = self.make()
        t.record_match_solve("default", (1024, 128), "xla", 0.02)
        info = t.solve_info("default")
        assert info == {"op": "match", "shape": "1024x128",
                        "backend": "xla", "compiled": True,
                        "seconds": 0.02}
        assert t.solve_info("nope") is None

    def test_compiles_excluded_from_latency_baseline(self):
        t = self.make(latency_min_samples=4)
        # alternating fresh shapes: every solve compiles, baseline stays
        # empty, so a storm of compiles can't read as a latency regression
        for i in range(8):
            t.record_match_solve("p", (64 * (i + 1), 64), "xla", 5.0)
        assert t.latency_regressions() == {}

    def test_latency_regression_detected(self):
        t = self.make(latency_window=32, latency_recent=4,
                      latency_min_samples=8)
        t.record_match_solve("p", (1024, 128), "xla", 9.0)  # compile run
        for _ in range(16):
            t.record_match_solve("p", (1024, 128), "xla", 0.010)
        assert t.latency_regressions() == {}
        for _ in range(4):
            t.record_match_solve("p", (1024, 128), "xla", 0.100)
        assert "p" in t.latency_regressions()
        health = t.health()
        assert not health["healthy"]
        assert "solve-latency-regression" in health["reasons"]

    def test_batched_solve_counts_once(self):
        t = self.make()
        before = global_registry.counter("obs.solve.count").value(
            {"op": "match_batched", "backend": "xla"})
        t.record_batched_match_solve(["a", "b"], (2, 1024, 128), "xla",
                                     0.05)
        after = global_registry.counter("obs.solve.count").value(
            {"op": "match_batched", "backend": "xla"})
        assert after == before + 1
        assert t.solve_info("a")["shape"] == "2x1024x128"
        assert t.solve_info("b")["op"] == "match_batched"

    def test_health_oom_risk(self):
        t = self.make(memory_stats_fn=lambda: {
            "bytes_in_use": 95, "bytes_limit": 100,
            "peak_bytes_in_use": 99, "utilization": 0.95})
        health = t.health()
        assert health["reasons"] == ["device-oom-risk"]
        assert health["checks"]["device_memory"]["utilization"] == 0.95

    def test_health_unobservable_memory(self):
        health = self.make().health()
        assert health["healthy"]
        assert health["checks"]["device_memory"] == {"observable": False}


def test_fetch_result_materializes_pytrees():
    import jax.numpy as jnp

    from cook_tpu.ops.match import MatchResult

    result = MatchResult(assignment=jnp.arange(4), new_avail=jnp.ones((2, 3)))
    fetched = fetch_result(result)
    assert isinstance(fetched.assignment, np.ndarray)
    assert isinstance(fetched.new_avail, np.ndarray)
    assert fetch_result(jnp.arange(3)).tolist() == [0, 1, 2]
