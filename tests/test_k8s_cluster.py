"""K8s backend tests (reference: test/cook/test/kubernetes/{api,controller,
compute_cluster}.clj): synthesized offers, controller state machine,
autoscaling, anti-entropy, failover recovery."""
import pytest

from cook_tpu.cluster.base import TaskSpec
from cook_tpu.cluster.k8s import (
    ExpectedState,
    FakeKubeApi,
    KubeCluster,
    KubeNode,
    KubePod,
    PodPhase,
)
from cook_tpu.models.entities import InstanceStatus, JobState, Pool
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler
from tests.conftest import FakeClock, make_job


def make_cluster(n_nodes=2, mem=4000.0, cpus=8.0):
    clock = FakeClock()
    api = FakeKubeApi([
        KubeNode(name=f"node{i}", mem=mem, cpus=cpus) for i in range(n_nodes)
    ])
    cluster = KubeCluster("k8s", api, clock)
    return clock, api, cluster


def spec(task_id, node, mem=100.0, cpus=1.0):
    return TaskSpec(task_id=task_id, job_uuid="j", user="u", command="c",
                    mem=mem, cpus=cpus, gpus=0.0, node_id=node, hostname=node)


def test_synthesized_offers_subtract_consumption():
    clock, api, cluster = make_cluster()
    offers = {o.node_id: o for o in cluster.pending_offers("default")}
    assert offers["node0"].mem == 4000.0
    cluster.launch_tasks("default", [spec("t1", "node0", mem=1000, cpus=2)])
    offers = {o.node_id: o for o in cluster.pending_offers("default")}
    assert offers["node0"].mem == 3000.0
    assert offers["node0"].cpus == 6.0
    assert offers["node0"].total_mem == 4000.0
    assert offers["node1"].mem == 4000.0


def test_controller_lifecycle_success():
    clock, api, cluster = make_cluster()
    events = []
    cluster.status_callback = lambda t, s, r: events.append((t, s, r))
    cluster.launch_tasks("default", [spec("t1", "node0")])
    assert cluster.expected["t1"] == ExpectedState.STARTING
    api.tick()  # pod starts running
    assert ("t1", InstanceStatus.RUNNING, None) in events
    assert cluster.expected["t1"] == ExpectedState.RUNNING
    api.finish_pod("t1")
    assert ("t1", InstanceStatus.SUCCESS, "normal-exit") in events
    # terminal pod is deleted
    assert api.pods.get("t1") is None


def test_controller_kill_deletes_pod():
    clock, api, cluster = make_cluster()
    events = []
    cluster.status_callback = lambda t, s, r: events.append((t, s, r))
    cluster.launch_tasks("default", [spec("t1", "node0")])
    api.tick()
    cluster.kill_task("t1")
    assert api.pods.get("t1") is None
    assert ("t1", InstanceStatus.FAILED, "killed-by-user") in events


def test_controller_pod_failure_reports_reason():
    clock, api, cluster = make_cluster()
    events = []
    cluster.status_callback = lambda t, s, r: events.append((t, s, r))
    cluster.launch_tasks("default", [spec("t1", "node0")])
    api.tick()
    api.finish_pod("t1", failed=True, reason="container-limitation-memory")
    assert ("t1", InstanceStatus.FAILED, "container-limitation-memory") in events


def test_node_loss_is_mea_culpa_failure():
    clock, api, cluster = make_cluster()
    events = []
    cluster.status_callback = lambda t, s, r: events.append((t, s, r))
    cluster.launch_tasks("default", [spec("t1", "node0")])
    api.tick()
    api.remove_node("node0")
    assert ("t1", InstanceStatus.FAILED, "node-removed") in events


def test_orphan_pod_killed_by_scan():
    clock, api, cluster = make_cluster()
    api.create_pod(KubePod(name="orphan", node_name="node0", mem=1, cpus=1,
                           phase=PodPhase.RUNNING))
    cluster.scan_all()
    assert api.pods.get("orphan") is None


def test_failover_recovery():
    # a pod from the previous leader exists BEFORE this leader's cluster
    # object attaches its watches (the real failover ordering:
    # initialize-cluster reconstructs expected state, then starts watches)
    clock = FakeClock()
    api = FakeKubeApi([KubeNode(name="node0", mem=4000, cpus=8)])
    api.create_pod(KubePod(name="t9", node_name="node0", mem=1, cpus=1,
                           phase=PodPhase.RUNNING))
    cluster = KubeCluster("k8s", api, clock)
    events = []
    cluster.status_callback = lambda t, s, r: events.append((t, s, r))
    cluster.determine_expected_state_on_startup({"t9"})
    assert cluster.expected["t9"] == ExpectedState.RUNNING
    api.finish_pod("t9")
    assert ("t9", InstanceStatus.SUCCESS, "normal-exit") in events


def test_autoscale_synthetic_pods_bounded():
    clock, api, cluster = make_cluster()
    demand = [spec(f"p{i}", "", mem=100, cpus=1) for i in range(200)]
    cluster.autoscale("default", demand)
    synth = cluster.synthetic_pods()
    assert len(synth) == 128  # max-pods-outstanding cap
    cluster.autoscale("default", demand)
    assert len(cluster.synthetic_pods()) == 128  # still capped


def test_end_to_end_with_scheduler():
    """Full stack on the k8s backend: submit -> match -> pod -> success."""
    clock = FakeClock()
    api = FakeKubeApi([KubeNode(name="node0", mem=4000, cpus=8)])
    cluster = KubeCluster("k8s", api, clock)
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    scheduler = Scheduler(store, [cluster])
    job = make_job(mem=100, cpus=1)
    store.submit_jobs([job])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == 1
    api.tick()
    assert store.jobs[job.uuid].state == JobState.RUNNING
    [task_id] = [i.task_id for i in store.job_instances(job.uuid)]
    api.finish_pod(task_id)
    assert store.jobs[job.uuid].state == JobState.COMPLETED
