"""Flight recorder + end-to-end correlation IDs + job-latency SLO
metrics (ISSUE 2): cycle records under a multi-pool workload, the
/debug/cycles surface, txn correlation through journal/replication/span
ring, and the tracing fixes (thread-entry leak, error tagging)."""
import threading

import pytest
import requests

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread
from cook_tpu.scheduler import flight_recorder as fr
from cook_tpu.scheduler.core import Scheduler
from cook_tpu.sim.simulator import SimConfig, Simulator, synth_trace
from cook_tpu.utils import tracing
from tests.conftest import FakeClock


# ------------------------------------------------------------------- recorder


def test_recorder_ring_and_job_reasons():
    rec = fr.FlightRecorder(capacity=2)
    for i in range(3):
        b = rec.begin("default", t_ms=i * 1000)
        with b.phase("tensor_build"):
            pass
        b.note_match(f"job-{i}", "host-a", f"task-{i}")
        b.note_skip("job-skip", fr.INSUFFICIENT_RESOURCES)
        rec.commit(b)
    records = rec.records()
    assert len(records) == 2  # bounded ring
    assert records[-1].cycle_id == 3
    assert rec.get(3) is not None and rec.get(1) is None
    cycle_id, code, _ = rec.job_reason("job-2")
    assert code == fr.MATCHED and cycle_id == 3
    _, code, detail = rec.job_reason("job-skip")
    assert code == fr.INSUFFICIENT_RESOURCES
    assert detail  # human text auto-filled from the code


def test_simulator_multipool_cycle_records():
    jobs, hosts = synth_trace(40, 6, n_users=3, seed=7)
    for j in jobs[::2]:
        j.pool = "alt"
    for h in hosts[::2]:
        h.pool = "alt"
    sim = Simulator(jobs, hosts, SimConfig(rebalance_every=2))
    result = sim.run()
    records = result.cycle_records
    assert records, "simulator produced no cycle records"
    assert {r["pool"] for r in records} == {"default", "alt"}
    matched = [r for r in records if r["matched_count"]]
    assert matched, "no cycle recorded a match"
    for r in matched:
        assert "tensor_build" in r["phases"] and "solve" in r["phases"] \
            and "launch" in r["phases"] and "rank" in r["phases"]
        assert r["device_s"] > 0 and r["host_s"] > 0
        assert r["total_s"] >= r["device_s"] + r["host_s"] - 1e-9
        assert all(m["job"] and m["host"] and m["task_id"]
                   for m in r["matched"])
    # every completed trace job was matched in SOME record
    matched_uuids = {m["job"] for r in records for m in r["matched"]}
    completed = {row["job_uuid"] for row in result.rows
                 if row["status"] == "success"}
    assert completed and completed <= matched_uuids
    # per-job reason codes: skips carry machine-readable codes
    codes = {s["code"] for r in records for s in r["skipped"]}
    assert codes <= {fr.NO_OFFERS, fr.CONSTRAINTS_FILTERED,
                     fr.INSUFFICIENT_RESOURCES, fr.LAUNCH_CAP,
                     fr.PORTS_EXHAUSTED, fr.LAUNCH_VETOED,
                     fr.NOT_CONSIDERED, fr.EXCEEDS_POOL_CAPACITY}


def test_simulator_batched_match_records_flagged():
    jobs, hosts = synth_trace(30, 6, n_users=2, seed=3)
    for j in jobs[::2]:
        j.pool = "alt"
    for h in hosts[::2]:
        h.pool = "alt"
    sim = Simulator(jobs, hosts, SimConfig(batched_match=True))
    result = sim.run()
    solved = [r for r in result.cycle_records if "solve" in r["phases"]]
    assert solved and all(r["batched"] for r in solved)
    # per-pool totals come from the pool's own attributed phases, not the
    # whole batch's builder-lifetime elapsed
    for r in solved:
        assert r["total_s"] == pytest.approx(r["device_s"] + r["host_s"])


def test_preemptions_annotated_with_dru():
    rec = fr.FlightRecorder()
    b = rec.begin("default", 0)
    rec.commit(b)
    rec.annotate_preemptions(
        "default",
        [fr.PreemptionRecord(job_uuid="j1", hostname="h1",
                             task_ids=["t1", "t2"], min_preempted_dru=0.37)],
        duration_s=0.01)
    record = rec.records()[-1]
    assert record.phases["preemption_search"] == pytest.approx(0.01)
    assert record.preemptions[0].min_preempted_dru == 0.37
    assert record.to_json()["preemptions"][0]["task_ids"] == ["t1", "t2"]


def test_not_considered_indexed_without_bloating_record():
    from cook_tpu.models.entities import Resources
    from cook_tpu.models.entities import Job
    from cook_tpu.scheduler.core import SchedulerConfig
    from cook_tpu.scheduler.matcher import MatchConfig

    store = JobStore()
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m", [MockHost(node_id="n0", hostname="n0", mem=4096, cpus=16)],
        clock=lambda: 0)
    sched = Scheduler(store, [cluster], SchedulerConfig(
        match=MatchConfig(max_jobs_considered=1)))
    store.submit_jobs([
        Job(uuid=f"w-{i}", user="u", command="x", priority=50 - i,
            pool="default",
            resources=Resources(mem=64, cpus=1)) for i in range(3)
    ])
    pool = store.pools["default"]
    sched.rank_cycle(pool)
    sched.match_cycle(pool)
    record = sched.recorder.records()[-1]
    assert record.considered == 1
    assert record.not_considered == 2
    # the uuids live in the per-job index, not the record
    assert all(s["code"] != fr.NOT_CONSIDERED for s in record.skipped)
    over_window = [u for u in ("w-0", "w-1", "w-2")
                   if sched.recorder.job_reason(u)[1] == fr.NOT_CONSIDERED]
    assert len(over_window) == 2


def test_lifecycle_first_match_only_observed_once():
    from cook_tpu.models.entities import InstanceStatus, Job, Resources
    from cook_tpu.scheduler.monitor import JobLifecycleTracker

    store = JobStore(clock=lambda: 50_000)
    store.set_pool(Pool(name="default"))
    tracker = JobLifecycleTracker(store)
    before = tracker._submit_to_matched.count({"pool": "default"})
    store.submit_jobs([Job(uuid="rj", user="u", command="x", max_retries=5,
                           pool="default",
                           resources=Resources(mem=64, cpus=1))])
    store.create_instance("rj", "t1", hostname="h")
    store.update_instance_state("t1", InstanceStatus.FAILED, "straggler")
    store.create_instance("rj", "t2", hostname="h")
    after = tracker._submit_to_matched.count({"pool": "default"})
    assert after - before == 1  # the retry match is not re-observed


def test_lifecycle_gated_on_passive_standby():
    from cook_tpu.models.entities import Job, Resources
    from cook_tpu.scheduler.monitor import JobLifecycleTracker

    store = JobStore(clock=lambda: 99_000)
    store.set_pool(Pool(name="default"))
    active = {"on": False}
    tracker = JobLifecycleTracker(store, enabled=lambda: active["on"])
    before = tracker._submit_to_matched.count({"pool": "default"})
    store.submit_jobs([Job(uuid="sb", user="u", command="x", pool="default",
                           resources=Resources(mem=64, cpus=1))])
    store.create_instance("sb", "st1", hostname="h")
    # passive: a replayed/replicated event must not observe
    assert tracker._submit_to_matched.count({"pool": "default"}) == before
    active["on"] = True
    store.submit_jobs([Job(uuid="sb2", user="u", command="x", pool="default",
                           resources=Resources(mem=64, cpus=1))])
    store.create_instance("sb2", "st2", hostname="h")
    assert tracker._submit_to_matched.count({"pool": "default"}) == before + 1


# ------------------------------------------------------------------- tracing


def test_span_thread_entries_reclaimed():
    before = tracing.active_thread_count()

    def worker():
        with tracing.span("leak-check"):
            pass

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert tracing.active_thread_count() == before


def test_span_error_tagging():
    with pytest.raises(ValueError):
        with tracing.span("boom-span"):
            raise ValueError("x")
    [entry] = [s for s in tracing.recent_spans(4096)
               if s["name"] == "boom-span"]
    assert entry["tags"]["error"] is True


def test_span_correlation_tagging():
    with tracing.correlate("txn-abc"):
        with tracing.span("inner-op"):
            pass
    assert tracing.current_correlation() is None
    [entry] = [s for s in tracing.recent_spans(4096)
               if s["name"] == "inner-op"]
    assert entry["tags"]["txn_id"] == "txn-abc"


# ------------------------------------------------------------ REST + txn flow


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    from cook_tpu.models import persistence

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    journal_path = str(tmp_path_factory.mktemp("journal") / "journal.jsonl")
    journal = persistence.attach_journal(store, journal_path)
    cluster = MockCluster(
        "mock",
        [MockHost(node_id=f"n{i}", hostname=f"n{i}", mem=4096, cpus=16)
         for i in range(4)],
        clock=clock,
    )
    scheduler = Scheduler(store, [cluster])
    api = CookApi(store, scheduler, ApiConfig(admins=("admin",)))
    srv = ServerThread(api).start()
    srv.clock = clock
    srv.store = store
    srv.scheduler = scheduler
    srv.cluster = cluster
    srv.api = api
    srv.journal_path = journal_path
    srv.journal = journal
    yield srv
    srv.stop()


def hdr(user="alice"):
    return {"X-Cook-Requesting-User": user}


def test_correlation_id_txn_to_journal_to_ack_to_spans(server):
    txn_id = "corr-e2e-0001"
    r = requests.post(
        f"{server.url}/jobs",
        json={"jobs": [{"command": "sleep", "mem": 64, "cpus": 1,
                        "uuid": "cccccccc-0000-0000-0000-000000000001"}]},
        headers={**hdr(), "X-Cook-Txn-Id": txn_id})
    assert r.status_code == 201, r.text
    # span ring: the txn.apply span carries the correlation id
    spans = [s for s in tracing.recent_spans(4096)
             if s["name"] == "txn.apply"
             and s["tags"].get("txn_id") == txn_id]
    assert spans and spans[0]["tags"]["op"] == "jobs/submit"
    # journal record: the txn/committed line journals the id
    server.journal.sync()
    from cook_tpu.models.persistence import read_journal

    committed = [e for e in read_journal(server.journal_path)
                 if e["kind"] == "txn/committed"
                 and e["data"].get("txn_id") == txn_id]
    assert committed and committed[0]["data"]["op"] == "jobs/submit"
    # replication ack: a follower reporting the id is recorded + spanned
    seq = server.store.last_seq()
    r = requests.post(f"{server.url}/replication/ack",
                      json={"follower": "standby-1", "seq": seq,
                            "durable": True, "last_txn_id": txn_id},
                      headers=hdr("admin"))
    assert r.status_code == 200
    assert server.api.replication_ack_meta["standby-1"]["last_txn_id"] \
        == txn_id
    ack_spans = [s for s in tracing.recent_spans(4096)
                 if s["name"] == "replication.ack"
                 and s["tags"].get("txn_id") == txn_id]
    assert ack_spans
    # and the whole trace is queryable by correlation id over REST
    r = requests.get(f"{server.url}/debug/spans",
                     params={"txn_id": txn_id}, headers=hdr())
    names = {s["name"] for s in r.json()["spans"]}
    assert {"txn.apply", "replication.ack"} <= names


def test_follower_tracks_last_txn_id():
    from cook_tpu.control.replication import JournalFollower

    store = JobStore()
    follower = JournalFollower(store, leader_url_fn=lambda: "")
    follower._apply([
        {"seq": 1, "kind": "txn/committed",
         "data": {"txn_id": "t-1", "op": "jobs/kill", "result": {}}},
    ])
    assert follower.last_txn_id == "t-1"


def test_debug_cycles_endpoint_and_unscheduled_enrichment(server):
    # one schedulable job, one job too big for any host
    r = requests.post(
        f"{server.url}/jobs",
        json={"jobs": [
            {"command": "ok", "mem": 100, "cpus": 1,
             "uuid": "dddddddd-0000-0000-0000-000000000001"},
            {"command": "big", "mem": 400000, "cpus": 400,
             "uuid": "dddddddd-0000-0000-0000-000000000002"},
        ]},
        headers=hdr())
    assert r.status_code == 201, r.text
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)

    r = requests.get(f"{server.url}/debug/cycles", headers=hdr())
    assert r.status_code == 200
    cycles = r.json()["cycles"]
    assert cycles
    record = cycles[-1]
    assert record["pool"] == "default"
    assert "rank" in record["phases"] and "launch" in record["phases"]
    assert any(m["job"] == "dddddddd-0000-0000-0000-000000000001"
               for m in record["matched"])
    [skip] = [s for s in record["skipped"]
              if s["job"] == "dddddddd-0000-0000-0000-000000000002"]
    assert skip["code"] in (fr.INSUFFICIENT_RESOURCES,
                            fr.CONSTRAINTS_FILTERED,
                            fr.EXCEEDS_POOL_CAPACITY)

    # single-record endpoint
    r = requests.get(f"{server.url}/debug/cycles/{record['cycle']}",
                     headers=hdr())
    assert r.status_code == 200 and r.json()["cycle"] == record["cycle"]
    assert requests.get(f"{server.url}/debug/cycles/999999",
                        headers=hdr()).status_code == 404

    # /unscheduled_jobs answers with the cycle's reason code
    r = requests.get(
        f"{server.url}/unscheduled_jobs",
        params={"job": "dddddddd-0000-0000-0000-000000000002"},
        headers=hdr())
    reasons = r.json()[0]["reasons"]
    enriched = [x for x in reasons
                if x.get("data", {}).get("reason_code") == skip["code"]]
    assert enriched and enriched[0]["data"]["cycle"] == record["cycle"]


def test_job_lifecycle_histograms_in_metrics(server):
    r = requests.post(
        f"{server.url}/jobs",
        json={"jobs": [{"command": "work", "mem": 100, "cpus": 1,
                        "uuid": "eeeeeeee-0000-0000-0000-000000000001"}]},
        headers=hdr())
    assert r.status_code == 201
    server.clock.advance(5_000)
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    server.clock.advance(60_000)
    server.cluster.advance_to(server.clock())
    job = server.store.jobs["eeeeeeee-0000-0000-0000-000000000001"]
    assert job.state.value == "completed"

    text = requests.get(f"{server.url}/metrics", headers=hdr()).text
    assert "cook_job_latency_submit_commit_ack_count" in text
    assert 'cook_job_latency_submit_to_matched_count{pool="default"}' in text
    assert "cook_job_latency_matched_to_running_count" in text
    assert 'cook_job_latency_end_to_end_count{pool="default"}' in text
    assert "cook_cycle_duration_count" in text
