"""Multi-cluster behavior (reference: test_multi_cluster.py +
compute_cluster.clj dynamic state machine): matching across clusters,
draining, deletion, reconciliation."""
import pytest

from cook_tpu.cluster.base import ClusterState
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import InstanceStatus, JobState, Pool
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler
from tests.conftest import FakeClock, make_job


def setup_two_clusters():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    c1 = MockCluster("east",
                     [MockHost(node_id="e0", hostname="e0", mem=2000, cpus=4)],
                     clock=clock)
    c2 = MockCluster("west",
                     [MockHost(node_id="w0", hostname="w0", mem=2000, cpus=4)],
                     clock=clock)
    scheduler = Scheduler(store, [c1, c2])
    return clock, store, c1, c2, scheduler


def test_jobs_spread_across_clusters():
    clock, store, c1, c2, scheduler = setup_two_clusters()
    jobs = [make_job(mem=1500, cpus=3) for _ in range(2)]
    store.submit_jobs(jobs)
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == 2
    clusters_used = {store.instances[t].compute_cluster
                     for t in outcome.launched_task_ids}
    assert clusters_used == {"east", "west"}


def test_draining_cluster_gets_no_new_work():
    clock, store, c1, c2, scheduler = setup_two_clusters()
    c1.set_state(ClusterState.DRAINING)
    jobs = [make_job(mem=500, cpus=1) for _ in range(3)]
    store.submit_jobs(jobs)
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    used = {store.instances[t].compute_cluster
            for t in outcome.launched_task_ids}
    assert used == {"west"}
    # draining can resume
    c1.set_state(ClusterState.RUNNING)
    # deleted is terminal
    c1.set_state(ClusterState.DRAINING)
    c1.set_state(ClusterState.DELETED)
    with pytest.raises(ValueError):
        c1.set_state(ClusterState.RUNNING)


def test_running_to_deleted_is_invalid():
    clock, store, c1, c2, scheduler = setup_two_clusters()
    with pytest.raises(ValueError):
        c1.set_state(ClusterState.DELETED)


def test_kill_routes_to_owning_cluster():
    clock, store, c1, c2, scheduler = setup_two_clusters()
    job = make_job(mem=1500, cpus=3)
    store.submit_jobs([job])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    [inst] = store.job_instances(job.uuid)
    owner = inst.compute_cluster
    store.kill_jobs([job.uuid])
    killed_on = c1 if owner == "east" else c2
    other = c2 if owner == "east" else c1
    assert killed_on.killed_count == 1
    assert other.killed_count == 0
    assert store.instances[inst.task_id].status == InstanceStatus.FAILED


def test_reconcile_fails_unknown_tasks():
    clock, store, c1, c2, scheduler = setup_two_clusters()
    job = make_job(max_retries=3)
    store.submit_jobs([job])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    scheduler.match_cycle(pool)
    [inst] = store.job_instances(job.uuid)
    # backend loses the task without reporting (e.g. agent wipe)
    c1.running.pop(inst.task_id, None)
    c2.running.pop(inst.task_id, None)
    fixed = scheduler.reconcile()
    assert fixed == [inst.task_id]
    # task-unknown is not mea-culpa but the job had retries
    assert store.jobs[job.uuid].state == JobState.WAITING


def test_cluster_launch_cap_respected():
    """A cluster's max_launchable bounds launches per cycle; surplus
    matches wait (filter-matches-for-ratelimit semantics)."""
    clock, store, c1, c2, scheduler = setup_two_clusters()
    c1.max_launchable = lambda: 1
    c2.max_launchable = lambda: 1
    jobs = [make_job(mem=100, cpus=1) for _ in range(6)]
    store.submit_jobs(jobs)
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == 2  # one per cluster
    running = [j for j in jobs if store.jobs[j.uuid].state == JobState.RUNNING]
    assert len(running) == 2
