"""JobClient + CLI tests over a live server (reference: cli/tests +
jobclient/python/tests)."""
import json

import pytest

from cook_tpu.client.cli import main as cli_main
from cook_tpu.client.jobclient import JobClient, JobClientError
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread
from cook_tpu.scheduler.core import Scheduler
from tests.conftest import FakeClock


@pytest.fixture(scope="module")
def server():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "mock",
        [MockHost(node_id=f"n{i}", hostname=f"n{i}", mem=4096, cpus=16)
         for i in range(2)],
        clock=clock,
    )
    scheduler = Scheduler(store, [cluster])
    api = CookApi(store, scheduler, ApiConfig())
    srv = ServerThread(api).start()
    srv.clock = clock
    srv.store = store
    srv.scheduler = scheduler
    srv.cluster = cluster
    yield srv
    srv.stop()


@pytest.fixture
def client(server):
    return JobClient(server.url, user="alice")


def test_client_submit_query_kill(client):
    uuids = client.submit([{"command": "echo 1"}, {"command": "echo 2"}])
    assert len(uuids) == 2
    jobs = client.query(uuids)
    assert all(j["status"] == "waiting" for j in jobs)
    client.kill(uuids)
    jobs = client.query(uuids)
    assert all(j["status"] == "completed" for j in jobs)


def test_client_wait(server, client):
    [uuid] = client.submit([{"command": "w", "expected_runtime": 10_000}])
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)

    def sleeper(_):
        server.clock.advance(20_000)
        server.cluster.advance_to(server.clock.now_ms)

    jobs = client.wait([uuid], timeout_s=10, poll_s=0.01, sleep=sleeper)
    assert jobs[0]["status"] == "completed"


def test_client_error_handling(client):
    with pytest.raises(JobClientError) as e:
        client.query_one("nonexistent-uuid")
    assert e.value.status == 404
    with pytest.raises(JobClientError):
        client.submit([{"mem": 100}])  # no command


def test_client_retry_and_reasons(server, client):
    [uuid] = client.submit([{"command": "r", "mem": 500000, "cpus": 1}])
    client.retry(uuid, 7)
    assert client.query_one(uuid)["max_retries"] == 7
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    reasons = client.unscheduled_reasons(uuid)
    assert reasons


def cli(server, *argv, user="alice"):
    return cli_main(["--config", server.cfg_path, "--user", user, *argv])


@pytest.fixture
def cfg(server, tmp_path):
    p = tmp_path / "cs.json"
    p.write_text(json.dumps(
        {"clusters": [{"name": "c1", "url": server.url}]}
    ))
    server.cfg_path = str(p)
    return str(p)


def test_cli_submit_show_kill(server, cfg, capsys):
    assert cli(server, "submit", "--mem", "64", "echo", "hello") == 0
    uuid = capsys.readouterr().out.strip()
    assert cli(server, "show", uuid) == 0
    out = capsys.readouterr().out
    assert "waiting" in out and uuid in out
    assert cli(server, "kill", uuid) == 0
    capsys.readouterr()
    assert cli(server, "show", uuid) == 0
    assert "completed" in capsys.readouterr().out


def test_cli_jobs_and_usage(server, cfg, capsys):
    cli(server, "submit", "sleep 1")
    capsys.readouterr()
    assert cli(server, "jobs") == 0
    assert "c1" in capsys.readouterr().out
    assert cli(server, "usage") == 0
    assert "mem" in capsys.readouterr().out


def test_cli_unknown_uuid(server, cfg, capsys):
    assert cli(server, "show", "no-such-uuid") == 1


def test_cli_timeline(server, cfg, capsys):
    assert cli(server, "submit", "--mem", "64", "tlwork") == 0
    uuid = capsys.readouterr().out.strip()
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    assert cli(server, "timeline", uuid) == 0
    out = capsys.readouterr().out
    assert uuid in out
    assert "submitted to pool default" in out
    assert "matched to" in out
    assert "launched task" in out
    assert "phases:" in out
    # --json emits the raw endpoint body
    assert cli(server, "timeline", uuid, "--json") == 0
    body = json.loads(capsys.readouterr().out)
    assert body["uuid"] == uuid
    assert [e["kind"] for e in body["events"]][0] == "submitted"


def test_cli_history_sparkline_and_index(server, cfg, capsys):
    # populate the health rollup gauge, then force two sample ticks so
    # counters/histograms have a window to difference over
    server.api.health_verdict()
    server.api.history.sample_once()
    server.api.history.sample_once()
    # no metric -> the series index
    assert cli(server, "history") == 0
    index_out = capsys.readouterr().out
    assert "obs.health" in index_out or "rest." in index_out
    # a gauge family renders a sparkline line per series
    assert cli(server, "history", "obs.health.degraded",
               "--window", "3600") == 0
    out = capsys.readouterr().out
    assert "obs.health.degraded" in out and "last=" in out
    # an unknown metric is a non-zero exit with a hint, not a traceback
    assert cli(server, "history", "no.such.metric") == 1
    assert "no points" in capsys.readouterr().err
    # --json round-trips
    assert cli(server, "history", "obs.health.degraded", "--json") == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["series"]


def test_cli_fleet_disabled_and_rendered(server, cfg, capsys):
    # no observatory wired -> the disabled stub, exit 0
    assert cli(server, "fleet") == 0
    assert "disabled" in capsys.readouterr().out
    from cook_tpu.obs.fleet import FleetObservatory

    server.api.fleet = FleetObservatory(
        self_url=server.url, incidents=server.api.incidents,
        self_verdict_fn=server.api.health_verdict)
    try:
        server.api.fleet.poll_once()
        assert cli(server, "fleet") == 0
        out = capsys.readouterr().out
        assert "ok" in out and server.url in out
        assert cli(server, "fleet", "--json") == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["enabled"] and parsed["nodes"]
    finally:
        server.api.fleet = None


def test_sparkline_shapes():
    from cook_tpu.client.cli import sparkline

    assert sparkline([]) == ""
    assert sparkline([3.0, 3.0, 3.0]) == "▁▁▁"
    ramp = sparkline([0, 1, 2, 3, 4, 5, 6, 7])
    assert ramp[0] == "▁" and ramp[-1] == "█"
    # long series downsample to the target width
    assert len(sparkline(list(range(1000)), width=40)) == 40


def test_cli_timeline_unknown_uuid(server, cfg, capsys):
    assert cli(server, "timeline", "no-such-uuid") == 1


def test_cli_admin_share_and_quota(server, cfg, capsys):
    assert cli_main(["--config", server.cfg_path, "--user", "admin",
                     "admin", "set-share", "--for-user", "zed",
                     "--mem", "500", "--cpus", "5"]) == 0
    capsys.readouterr()
    assert cli_main(["--config", server.cfg_path, "--user", "admin",
                     "admin", "set-quota", "--for-user", "zed",
                     "--count", "4"]) == 0
    capsys.readouterr()
    assert server.store.get_share("zed", "default").mem == 500
    assert server.store.get_quota("zed", "default").count == 4


def test_debug_endpoint(server):
    import requests

    r = requests.get(f"{server.url}/debug")
    assert r.status_code == 200
    assert r.json()["healthy"] is True


def test_cli_config_management(tmp_path, capsys):
    path = str(tmp_path / "fed.json")
    assert cli_main(["--config", path, "config",
                     "--add-cluster", "east", "http://e:1"]) == 0
    capsys.readouterr()
    assert cli_main(["--config", path, "config",
                     "--add-cluster", "west", "http://w:1"]) == 0
    capsys.readouterr()
    assert cli_main(["--config", path, "config"]) == 0
    out = capsys.readouterr().out
    assert "east" in out and "west" in out
    assert cli_main(["--config", path, "config",
                     "--remove-cluster", "east"]) == 0
    capsys.readouterr()
    assert cli_main(["--config", path, "config"]) == 0
    assert "east" not in capsys.readouterr().out


def test_typed_views(server, client):
    [uuid] = client.submit([{"command": "v", "expected_runtime": 5_000}])
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    server.clock.advance(10_000)
    server.cluster.advance_to(server.clock.now_ms)
    [view] = client.query_views([uuid])
    assert view.uuid == uuid
    assert view.completed and view.succeeded
    assert view.last_instance.status == "success"
    assert view.last_instance.hostname.startswith("n")
    assert view.retries_remaining == 0


def test_cli_why(server, cfg, capsys):
    # a job too big for any current host waits with an explanation
    [uuid] = JobClient(server.url, user="alice").submit(
        [{"command": "big", "mem": 9999, "cpus": 15}])
    for _ in range(2):
        pool = server.store.pools["default"]
        server.scheduler.rank_cycle(pool)
        server.scheduler.match_cycle(pool)
    assert cli_main(["--config", server.cfg_path, "--user", "alice",
                     "why", uuid]) == 0
    out = capsys.readouterr().out
    assert "waiting" in out
    assert "-" in out  # at least one reason line


def test_client_submit_gang_places_atomically(server, client):
    uuids = client.submit(
        [{"command": "gangwork", "mem": 64, "expected_runtime": 5_000}] * 2,
        gang_size=2)
    assert len(uuids) == 2
    jobs = client.query(uuids)
    assert all(j["gang_size"] == 2 for j in jobs)
    groups = {j["groups"][0] for j in jobs}
    assert len(groups) == 1, "gang members must share one group"
    pool = server.store.pools["default"]
    server.scheduler.rank_cycle(pool)
    server.scheduler.match_cycle(pool)
    jobs = client.query(uuids)
    hosts = {i["hostname"] for j in jobs for i in j["instances"]}
    assert all(j["status"] == "running" for j in jobs)
    assert len(hosts) == 2, "gang members must land on distinct hosts"
    server.clock.advance(10_000)
    server.cluster.advance_to(server.clock.now_ms)


def test_client_gang_size_batch_mismatch(client):
    with pytest.raises(ValueError):
        client.submit([{"command": "x"}], gang_size=3)
    # server-side: gang_size without a group is rejected
    with pytest.raises(JobClientError):
        client.submit([{"command": "x", "gang_size": 2},
                       {"command": "x", "gang_size": 2}])


def test_cli_submit_gang_timeline_renders_wait(server, cfg, capsys):
    # a 3-gang on a 2-host fleet can never assemble: the timeline must
    # attribute the wait to gang-incomplete with the best-block detail
    assert cli(server, "submit", "--gang-size", "3", "--mem", "64",
               "gangwait") == 0
    uuids = capsys.readouterr().out.split()
    assert len(uuids) == 3
    pool = server.store.pools["default"]
    for _ in range(3):
        server.scheduler.rank_cycle(pool)
        server.scheduler.match_cycle(pool)
    assert cli(server, "timeline", uuids[0]) == 0
    out = capsys.readouterr().out
    assert "gang-incomplete" in out
    assert "hosts free" in out
