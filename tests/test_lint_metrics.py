"""tools/lint_metrics.py: the static metrics + tracing lint, wired into
the tier-1 run — the repo itself must stay clean."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))

import lint_metrics  # noqa: E402

REPO_ROOT = str(pathlib.Path(__file__).parent.parent)


def lint_source(src: str):
    return lint_metrics.lint_sites(
        lint_metrics.collect_sites(src, "x.py"),
        lint_metrics.collect_span_sites(src, "x.py"))


def lint_files(**sources):
    sites, span_sites = [], []
    for path, src in sources.items():
        sites.extend(lint_metrics.collect_sites(src, path))
        span_sites.extend(lint_metrics.collect_span_sites(src, path))
    return lint_metrics.lint_sites(sites, span_sites)


def test_repo_metrics_are_clean():
    result = lint_metrics.lint_tree(REPO_ROOT)
    assert result.ok, "\n".join(result.errors)
    # sanity: the walker actually found the registry + span call sites
    assert len(result.sites) > 10
    assert len(result.span_sites) >= 4


def test_conflicting_types_detected():
    result = lint_source(
        "global_registry.counter('match.matched', 'help')\n"
        "global_registry.gauge('match.matched', 'help')\n")
    assert not result.ok
    assert any("conflicting types" in e for e in result.errors)


def test_same_type_duplicates_allowed():
    result = lint_source(
        "global_registry.counter('a.b', 'what a.b counts')\n"
        "global_registry.counter('a.b')\n")
    assert result.ok


def test_invalid_prometheus_identifier_detected():
    result = lint_source("global_registry.counter('has space', 'h')\n")
    assert not result.ok
    assert "invalid Prometheus identifier" in result.errors[0]


def test_dots_and_dashes_map_to_underscores():
    assert lint_metrics.rendered_name("a.b-c") == "cook_a_b_c"
    assert lint_source("global_registry.gauge('a.b-c', 'h')\n").ok


def test_dynamic_names_skipped_but_fragments_checked():
    ok = lint_source('global_registry.histogram(f"span.{name}", "h")\n')
    assert ok.ok
    assert ok.sites[0].dynamic
    bad = lint_source('global_registry.histogram(f"sp an.{name}", "h")\n')
    assert not bad.ok


def test_attribute_qualified_registry_matches():
    result = lint_source(
        "metrics.global_registry.counter('x', 'h')\n"
        "metrics.global_registry.histogram('x', 'h')\n")
    assert not result.ok


# ------------------------------------------------------------- HELP rule


def test_metric_without_help_rejected():
    result = lint_source("global_registry.counter('no.help')\n")
    assert not result.ok
    assert "without HELP" in result.errors[0]


def test_help_at_one_site_vouches_for_siblings():
    # .inc()-style re-registrations without help are fine as long as ONE
    # site documents the name
    result = lint_source(
        "global_registry.counter('a.b', 'what a.b counts').inc()\n"
        "global_registry.counter('a.b').inc(2)\n")
    assert result.ok


def test_help_keyword_counts():
    assert lint_source(
        "global_registry.gauge('a', help_='documented')\n").ok
    assert not lint_source("global_registry.gauge('a', help_='')\n").ok


def test_dynamic_metric_requires_help_at_site():
    assert not lint_source(
        'global_registry.histogram(f"span.{name}")\n').ok


def test_aliased_factory_resolved():
    # the monitor-gauge idiom: g = global_registry.gauge; g("name")
    src = ("g = global_registry.gauge\n"
           "g('monitor.x', 'help')\n"
           "g('monitor.x')\n")
    result = lint_source(src)
    assert result.ok
    assert len(result.sites) == 2
    bad = lint_source("g = global_registry.gauge\ng('monitor.y')\n")
    assert not bad.ok and "without HELP" in bad.errors[0]


def test_alias_type_conflict_detected():
    result = lint_source(
        "g = global_registry.gauge\n"
        "g('dual', 'h')\n"
        "global_registry.counter('dual', 'h')\n")
    assert not result.ok
    assert any("conflicting types" in e for e in result.errors)


# ------------------------------------------------------------ span rules


def test_span_names_must_match_grammar():
    assert lint_source("with span('match_cycle', pool=p): pass\n").ok
    bad = lint_source("with span('match-cycle'): pass\n")
    assert not bad.ok
    assert "[a-z0-9_.]" in bad.errors[0]
    assert not lint_source("tracing.span('Match.Cycle')\n").ok


def test_record_event_names_linted():
    assert lint_source("tracing.record_event('replication.ack')\n").ok
    assert not lint_source("tracing.record_event('Replication Ack')\n").ok


def test_span_reuse_within_one_file_allowed():
    assert lint_source(
        "span('cycle.work')\nspan('cycle.work')\n").ok


def test_duplicate_span_across_files_rejected():
    result = lint_files(**{
        "a.py": "span('shared.name')\n",
        "b.py": "span('shared.name')\n",
    })
    assert not result.ok
    assert "multiple modules" in result.errors[0]


def test_dynamic_span_fragments_checked():
    assert lint_source('span(f"cycle.{phase}")\n').ok
    assert not lint_source('span(f"Cycle {phase}")\n').ok


# ----------------------------------------------------- doc-drift rule


def test_documented_names_parsing():
    exact, prefixes = lint_metrics.documented_names(
        "| `jobs_submitted` | counter |\n"
        "| `monitor.*` | gauge |\n"
        "| `obs.device.mem_*` | gauge |\n"
        "| `a.b` / `c-d` | counter |\n")
    assert {"jobs_submitted", "a.b", "c-d"} <= exact
    assert "monitor." in prefixes and "obs.device.mem_" in prefixes


def test_doc_coverage_flags_undocumented_metric():
    result = lint_source("global_registry.counter('brand.new', 'h')\n")
    assert result.ok
    lint_metrics.lint_doc_coverage(result, "| `other.metric` | counter |",
                                   "docs/observability.md")
    assert not result.ok
    assert "not in the docs/observability.md catalog" in result.errors[0]


def test_doc_coverage_accepts_exact_and_wildcard():
    result = lint_source(
        "global_registry.counter('covered.exact', 'h')\n"
        "global_registry.gauge('family.member.x', 'h')\n")
    lint_metrics.lint_doc_coverage(
        result, "`covered.exact` and `family.*`", "docs/observability.md")
    assert result.ok


def test_doc_coverage_skips_dynamic_names():
    result = lint_source('global_registry.histogram(f"span.{n}", "h")\n')
    lint_metrics.lint_doc_coverage(result, "nothing documented",
                                   "docs/observability.md")
    assert result.ok


def test_tree_lint_checks_repo_doc_catalog(tmp_path):
    """A cook_tpu/-shaped tree with a catalog gets the drift check; the
    same tree without the doc is linted without it."""
    (tmp_path / "cook_tpu").mkdir()
    (tmp_path / "cook_tpu" / "a.py").write_text(
        "global_registry.counter('undocumented.name', 'h')\n")
    assert lint_metrics.lint_tree(str(tmp_path)).ok  # no catalog -> skip
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text("| `other` |")
    result = lint_metrics.lint_tree(str(tmp_path))
    assert not result.ok
    assert "undocumented.name" in result.errors[0]


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "a.py").write_text(
        "global_registry.counter('fine.name', 'help')\n")
    assert lint_metrics.main([str(clean)]) == 0
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "a.py").write_text(
        "global_registry.counter('n', 'h')\n"
        "global_registry.gauge('n', 'h')\n")
    assert lint_metrics.main([str(dirty)]) == 1
    spans = tmp_path / "spans"
    spans.mkdir()
    (spans / "a.py").write_text("span('Bad-Name')\n")
    assert lint_metrics.main([str(spans)]) == 1
