"""tools/lint_metrics.py: the static metrics + tracing lint, wired into
the tier-1 run — the repo itself must stay clean."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))

import lint_metrics  # noqa: E402

REPO_ROOT = str(pathlib.Path(__file__).parent.parent)


def lint_source(src: str):
    return lint_metrics.lint_sites(
        lint_metrics.collect_sites(src, "x.py"),
        lint_metrics.collect_span_sites(src, "x.py"))


def lint_files(**sources):
    sites, span_sites = [], []
    for path, src in sources.items():
        sites.extend(lint_metrics.collect_sites(src, path))
        span_sites.extend(lint_metrics.collect_span_sites(src, path))
    return lint_metrics.lint_sites(sites, span_sites)


def test_repo_metrics_are_clean():
    result = lint_metrics.lint_tree(REPO_ROOT)
    assert result.ok, "\n".join(result.errors)
    # sanity: the walker actually found the registry + span call sites
    assert len(result.sites) > 10
    assert len(result.span_sites) >= 4


def test_conflicting_types_detected():
    result = lint_source(
        "global_registry.counter('match.matched', 'help')\n"
        "global_registry.gauge('match.matched', 'help')\n")
    assert not result.ok
    assert any("conflicting types" in e for e in result.errors)


def test_same_type_duplicates_allowed():
    result = lint_source(
        "global_registry.counter('a.b', 'what a.b counts')\n"
        "global_registry.counter('a.b')\n")
    assert result.ok


def test_invalid_prometheus_identifier_detected():
    result = lint_source("global_registry.counter('has space', 'h')\n")
    assert not result.ok
    assert "invalid Prometheus identifier" in result.errors[0]


def test_dots_and_dashes_map_to_underscores():
    assert lint_metrics.rendered_name("a.b-c") == "cook_a_b_c"
    assert lint_source("global_registry.gauge('a.b-c', 'h')\n").ok


def test_dynamic_names_skipped_but_fragments_checked():
    ok = lint_source('global_registry.histogram(f"span.{name}", "h")\n')
    assert ok.ok
    assert ok.sites[0].dynamic
    bad = lint_source('global_registry.histogram(f"sp an.{name}", "h")\n')
    assert not bad.ok


def test_attribute_qualified_registry_matches():
    result = lint_source(
        "metrics.global_registry.counter('x', 'h')\n"
        "metrics.global_registry.histogram('x', 'h')\n")
    assert not result.ok


# ------------------------------------------------------------- HELP rule


def test_metric_without_help_rejected():
    result = lint_source("global_registry.counter('no.help')\n")
    assert not result.ok
    assert "without HELP" in result.errors[0]


def test_help_at_one_site_vouches_for_siblings():
    # .inc()-style re-registrations without help are fine as long as ONE
    # site documents the name
    result = lint_source(
        "global_registry.counter('a.b', 'what a.b counts').inc()\n"
        "global_registry.counter('a.b').inc(2)\n")
    assert result.ok


def test_help_keyword_counts():
    assert lint_source(
        "global_registry.gauge('a', help_='documented')\n").ok
    assert not lint_source("global_registry.gauge('a', help_='')\n").ok


def test_dynamic_metric_requires_help_at_site():
    assert not lint_source(
        'global_registry.histogram(f"span.{name}")\n').ok


def test_aliased_factory_resolved():
    # the monitor-gauge idiom: g = global_registry.gauge; g("name")
    src = ("g = global_registry.gauge\n"
           "g('monitor.x', 'help')\n"
           "g('monitor.x')\n")
    result = lint_source(src)
    assert result.ok
    assert len(result.sites) == 2
    bad = lint_source("g = global_registry.gauge\ng('monitor.y')\n")
    assert not bad.ok and "without HELP" in bad.errors[0]


def test_alias_type_conflict_detected():
    result = lint_source(
        "g = global_registry.gauge\n"
        "g('dual', 'h')\n"
        "global_registry.counter('dual', 'h')\n")
    assert not result.ok
    assert any("conflicting types" in e for e in result.errors)


# ------------------------------------------------------------ span rules


def test_span_names_must_match_grammar():
    assert lint_source("with span('match_cycle', pool=p): pass\n").ok
    bad = lint_source("with span('match-cycle'): pass\n")
    assert not bad.ok
    assert "[a-z0-9_.]" in bad.errors[0]
    assert not lint_source("tracing.span('Match.Cycle')\n").ok


def test_record_event_names_linted():
    assert lint_source("tracing.record_event('replication.ack')\n").ok
    assert not lint_source("tracing.record_event('Replication Ack')\n").ok


def test_span_reuse_within_one_file_allowed():
    assert lint_source(
        "span('cycle.work')\nspan('cycle.work')\n").ok


def test_duplicate_span_across_files_rejected():
    result = lint_files(**{
        "a.py": "span('shared.name')\n",
        "b.py": "span('shared.name')\n",
    })
    assert not result.ok
    assert "multiple modules" in result.errors[0]


def test_dynamic_span_fragments_checked():
    assert lint_source('span(f"cycle.{phase}")\n').ok
    assert not lint_source('span(f"Cycle {phase}")\n').ok


# ----------------------------------------------------- doc-drift rule


def test_documented_names_parsing():
    exact, prefixes = lint_metrics.documented_names(
        "| `jobs_submitted` | counter |\n"
        "| `monitor.*` | gauge |\n"
        "| `obs.device.mem_*` | gauge |\n"
        "| `a.b` / `c-d` | counter |\n")
    assert {"jobs_submitted", "a.b", "c-d"} <= exact
    assert "monitor." in prefixes and "obs.device.mem_" in prefixes


def test_doc_coverage_flags_undocumented_metric():
    result = lint_source("global_registry.counter('brand.new', 'h')\n")
    assert result.ok
    lint_metrics.lint_doc_coverage(result, "| `other.metric` | counter |",
                                   "docs/observability.md")
    assert not result.ok
    assert "not in the docs/observability.md catalog" in result.errors[0]


def test_doc_coverage_accepts_exact_and_wildcard():
    result = lint_source(
        "global_registry.counter('covered.exact', 'h')\n"
        "global_registry.gauge('family.member.x', 'h')\n")
    lint_metrics.lint_doc_coverage(
        result, "`covered.exact` and `family.*`", "docs/observability.md")
    assert result.ok


def test_doc_coverage_skips_dynamic_names():
    result = lint_source('global_registry.histogram(f"span.{n}", "h")\n')
    lint_metrics.lint_doc_coverage(result, "nothing documented",
                                   "docs/observability.md")
    assert result.ok


def test_tree_lint_checks_repo_doc_catalog(tmp_path):
    """A cook_tpu/-shaped tree with a catalog gets the drift check; the
    same tree without the doc is linted without it."""
    (tmp_path / "cook_tpu").mkdir()
    (tmp_path / "cook_tpu" / "a.py").write_text(
        "global_registry.counter('undocumented.name', 'h')\n")
    assert lint_metrics.lint_tree(str(tmp_path)).ok  # no catalog -> skip
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text("| `other` |")
    result = lint_metrics.lint_tree(str(tmp_path))
    assert not result.ok
    assert "undocumented.name" in result.errors[0]


# -------------------------------------------------- reverse doc-drift rule


CATALOG_DOC = """# Observability
Prose backticks like `GET /debug/health` and `utils/config.py` must
never be treated as catalog rows.

## Metric catalog (selected)

| name (registry) | type | labels | meaning |
|---|---|---|---|
| `covered.exact` | counter | — | a live metric |
| `journal.appends` / `journal.bytes` | counter | — | two names, one row |
| `family.*` | gauge | pool | a wildcard family |
| `span.<name>` | histogram | tags | dynamic family, constant head |

## Another section

| `not.a.catalog.row` | whatever |
"""


def test_reverse_drift_flags_stale_catalog_row():
    result = lint_source(
        "global_registry.counter('covered.exact', 'h')\n"
        "global_registry.counter('journal.appends', 'h')\n"
        "global_registry.gauge('family.member', 'h')\n"
        'global_registry.histogram(f"span.{n}", "h")\n')
    lint_metrics.lint_reverse_doc_drift(result, CATALOG_DOC,
                                        "docs/observability.md")
    # `journal.bytes` shares a row with a registered sibling but is
    # itself unregistered -> flagged; everything else is vouched for
    # (exact, wildcard family, dynamic `span.` head), and the other
    # sections' backticks are ignored entirely
    assert [e for e in result.errors] \
        == [e for e in result.errors if "journal.bytes" in e]
    assert len(result.errors) == 1
    assert "prune the row" in result.errors[0]


def test_reverse_drift_wildcard_needs_at_least_one_member():
    doc = ("## Metric catalog\n"
           "| name | type |\n|---|---|\n"
           "| `ghost.*` | gauge |\n")
    result = lint_source("global_registry.gauge('other.name', 'h')\n")
    lint_metrics.lint_reverse_doc_drift(result, doc, "docs/o.md")
    assert not result.ok and "ghost.*" in result.errors[0]
    result = lint_source("global_registry.gauge('ghost.member', 'h')\n")
    lint_metrics.lint_reverse_doc_drift(result, doc, "docs/o.md")
    assert result.ok


def test_reverse_drift_placeholder_rows_are_checked_not_skipped():
    """A `span.<name>`-style row normalizes to a `span.*` wildcard — it
    must be CHECKED (and fail when the dynamic family disappears), not
    silently skipped because `<` can't appear in a metric name."""
    doc = ("## Metric catalog\n| n |\n|---|\n"
           "| `span.<name>` | histogram |\n")
    rows = lint_metrics.catalog_rows(doc)
    assert rows == [(4, ["span.*"])]
    vouched = lint_source('global_registry.histogram(f"span.{n}", "h")\n')
    lint_metrics.lint_reverse_doc_drift(vouched, doc, "docs/o.md")
    assert vouched.ok
    orphaned = lint_source("global_registry.gauge('other', 'h')\n")
    lint_metrics.lint_reverse_doc_drift(orphaned, doc, "docs/o.md")
    assert not orphaned.ok and "span.*" in orphaned.errors[0]


def test_reverse_drift_line_numbers_point_at_the_row():
    lines = CATALOG_DOC.splitlines()
    rows = lint_metrics.catalog_rows(CATALOG_DOC)
    for lineno, tokens in rows:
        for token in tokens:
            base = token.rstrip("*").replace("<name>", "")
            assert base.rstrip(".") in lines[lineno - 1]
    # rows come only from the catalog section's table
    all_tokens = [t for _, tokens in rows for t in tokens]
    assert "not.a.catalog.row" not in all_tokens
    assert "GET" not in all_tokens


def test_constant_name_registration_is_resolved():
    """A registration through a file-local string constant participates
    in both drift directions (shard/replica.py's
    `_STALENESS_GAUGE_NAME` idiom)."""
    src = ('_NAME = "shard.via_constant"\n'
           "global_registry.gauge(_NAME, 'h')\n")
    result = lint_source(src)
    assert [s.name for s in result.sites] == ["shard.via_constant"]
    assert not result.sites[0].dynamic
    doc = ("## Metric catalog\n| n |\n|---|\n"
           "| `shard.via_constant` | gauge |\n")
    lint_metrics.lint_reverse_doc_drift(result, doc, "docs/o.md")
    assert result.ok
    # a REBOUND name is ambiguous and must not vouch for anything
    rebound = lint_source('X = "a.b"\nX = "c.d"\n'
                          "global_registry.gauge(X, 'h')\n")
    assert rebound.sites == []


def test_repo_catalog_survives_reverse_check():
    result = lint_metrics.lint_tree(REPO_ROOT)
    assert result.ok, "\n".join(result.errors)


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "a.py").write_text(
        "global_registry.counter('fine.name', 'help')\n")
    assert lint_metrics.main([str(clean)]) == 0
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "a.py").write_text(
        "global_registry.counter('n', 'h')\n"
        "global_registry.gauge('n', 'h')\n")
    assert lint_metrics.main([str(dirty)]) == 1
    spans = tmp_path / "spans"
    spans.mkdir()
    (spans / "a.py").write_text("span('Bad-Name')\n")
    assert lint_metrics.main([str(spans)]) == 1
