"""tools/lint_metrics.py: the static metrics-registry lint, wired into
the tier-1 run — the repo itself must stay clean."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))

import lint_metrics  # noqa: E402

REPO_ROOT = str(pathlib.Path(__file__).parent.parent)


def lint_source(src: str):
    return lint_metrics.lint_sites(lint_metrics.collect_sites(src, "x.py"))


def test_repo_metrics_are_clean():
    result = lint_metrics.lint_tree(REPO_ROOT)
    assert result.ok, "\n".join(result.errors)
    # sanity: the walker actually found the registry call sites
    assert len(result.sites) > 10


def test_conflicting_types_detected():
    result = lint_source(
        "global_registry.counter('match.matched')\n"
        "global_registry.gauge('match.matched')\n")
    assert not result.ok
    assert "conflicting types" in result.errors[0]


def test_same_type_duplicates_allowed():
    result = lint_source(
        "global_registry.counter('a.b')\n"
        "global_registry.counter('a.b')\n")
    assert result.ok


def test_invalid_prometheus_identifier_detected():
    result = lint_source("global_registry.counter('has space')\n")
    assert not result.ok
    assert "invalid Prometheus identifier" in result.errors[0]


def test_dots_and_dashes_map_to_underscores():
    assert lint_metrics.rendered_name("a.b-c") == "cook_a_b_c"
    assert lint_source("global_registry.gauge('a.b-c')\n").ok


def test_dynamic_names_skipped_but_fragments_checked():
    ok = lint_source('global_registry.histogram(f"span.{name}")\n')
    assert ok.ok
    assert ok.sites[0].dynamic
    bad = lint_source('global_registry.histogram(f"sp an.{name}")\n')
    assert not bad.ok


def test_attribute_qualified_registry_matches():
    result = lint_source(
        "metrics.global_registry.counter('x')\n"
        "metrics.global_registry.histogram('x')\n")
    assert not result.ok


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "a.py").write_text("global_registry.counter('fine.name')\n")
    assert lint_metrics.main([str(clean)]) == 0
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "a.py").write_text(
        "global_registry.counter('n')\nglobal_registry.gauge('n')\n")
    assert lint_metrics.main([str(dirty)]) == 1
