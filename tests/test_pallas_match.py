"""Pallas fused best-node kernel vs the numpy oracle (interpret mode on
CPU; compiled path exercised on real TPU by bench/round driver)."""
import numpy as np
import pytest

import jax.numpy as jnp

from cook_tpu.ops.common import BIG
from cook_tpu.ops.pallas_match import best_node


def oracle(demands, avail, totals, valid):
    k, n = len(demands), len(avail)
    out_v = np.full(k, -BIG, dtype=np.float64)
    out_i = np.full(k, -1, dtype=np.int64)
    for a in range(k):
        for i in range(n):
            if not valid[i]:
                continue
            if np.any(avail[i] < demands[a]):
                continue
            fit = 0.5 * (
                (totals[i, 0] - avail[i, 0] + demands[a, 0]) / totals[i, 0]
                + (totals[i, 1] - avail[i, 1] + demands[a, 1]) / totals[i, 1]
            )
            if fit > out_v[a]:
                out_v[a], out_i[a] = fit, i
    return out_v, out_i


@pytest.mark.parametrize("seed", range(3))
def test_best_node_parity(seed):
    rng = np.random.default_rng(seed)
    k, n = 16, 256
    demands = np.stack([
        rng.uniform(100, 4000, k), rng.uniform(0.5, 8, k), np.zeros(k)
    ], axis=-1).astype(np.float32)
    totals = np.stack([
        rng.uniform(4000, 64000, n), rng.uniform(8, 64, n)
    ], axis=-1).astype(np.float32)
    avail = np.concatenate([
        totals * rng.uniform(0.1, 1.0, (n, 1)).astype(np.float32),
        np.zeros((n, 1), np.float32),
    ], axis=-1)
    valid = rng.uniform(size=n) > 0.2

    want_v, want_i = oracle(demands, avail, totals, valid)
    got_v, got_i = best_node(
        jnp.asarray(demands), jnp.asarray(avail), jnp.asarray(totals),
        jnp.asarray(valid), block_jobs=8, block_nodes=128, interpret=True,
    )
    got_v, got_i = np.asarray(got_v), np.asarray(got_i)
    found = want_i >= 0
    np.testing.assert_array_equal(got_i[~found], -1)
    np.testing.assert_array_equal(got_i[found], want_i[found])
    np.testing.assert_allclose(got_v[found], want_v[found], rtol=1e-5)


def test_best_node_infeasible_everything():
    k, n = 8, 128
    demands = np.full((k, 3), 1e9, dtype=np.float32)
    totals = np.ones((n, 2), dtype=np.float32)
    avail = np.concatenate([totals, np.zeros((n, 1), np.float32)], axis=-1)
    got_v, got_i = best_node(
        jnp.asarray(demands), jnp.asarray(avail), jnp.asarray(totals),
        jnp.ones(n, bool), block_jobs=8, block_nodes=128, interpret=True,
    )
    assert np.all(np.asarray(got_i) == -1)
