"""Pallas fused best-node kernel vs the numpy oracle (interpret mode on
CPU; compiled path exercised on real TPU by bench/round driver)."""
import numpy as np
import pytest

import jax.numpy as jnp

from cook_tpu.ops.common import BIG
from cook_tpu.ops.pallas_match import best_node


def oracle(demands, avail, totals, valid):
    k, n = len(demands), len(avail)
    out_v = np.full(k, -BIG, dtype=np.float64)
    out_i = np.full(k, -1, dtype=np.int64)
    for a in range(k):
        for i in range(n):
            if not valid[i]:
                continue
            if np.any(avail[i] < demands[a]):
                continue
            fit = 0.5 * (
                (totals[i, 0] - avail[i, 0] + demands[a, 0]) / totals[i, 0]
                + (totals[i, 1] - avail[i, 1] + demands[a, 1]) / totals[i, 1]
            )
            if fit > out_v[a]:
                out_v[a], out_i[a] = fit, i
    return out_v, out_i


@pytest.mark.parametrize("seed", range(3))
def test_best_node_parity(seed):
    rng = np.random.default_rng(seed)
    k, n = 16, 256
    demands = np.stack([
        rng.uniform(100, 4000, k), rng.uniform(0.5, 8, k), np.zeros(k)
    ], axis=-1).astype(np.float32)
    totals = np.stack([
        rng.uniform(4000, 64000, n), rng.uniform(8, 64, n)
    ], axis=-1).astype(np.float32)
    avail = np.concatenate([
        totals * rng.uniform(0.1, 1.0, (n, 1)).astype(np.float32),
        np.zeros((n, 1), np.float32),
    ], axis=-1)
    valid = rng.uniform(size=n) > 0.2

    want_v, want_i = oracle(demands, avail, totals, valid)
    got_v, got_i = best_node(
        jnp.asarray(demands), jnp.asarray(avail), jnp.asarray(totals),
        jnp.asarray(valid), block_jobs=8, block_nodes=128, interpret=True,
    )
    got_v, got_i = np.asarray(got_v), np.asarray(got_i)
    found = want_i >= 0
    np.testing.assert_array_equal(got_i[~found], -1)
    np.testing.assert_array_equal(got_i[found], want_i[found])
    np.testing.assert_allclose(got_v[found], want_v[found], rtol=1e-5)


def test_best_node_infeasible_everything():
    k, n = 8, 128
    demands = np.full((k, 3), 1e9, dtype=np.float32)
    totals = np.ones((n, 2), dtype=np.float32)
    avail = np.concatenate([totals, np.zeros((n, 1), np.float32)], axis=-1)
    got_v, got_i = best_node(
        jnp.asarray(demands), jnp.asarray(avail), jnp.asarray(totals),
        jnp.ones(n, bool), block_jobs=8, block_nodes=128, interpret=True,
    )
    assert np.all(np.asarray(got_i) == -1)


@pytest.mark.parametrize("seed", range(3))
def test_best_node_constraint_mask(seed):
    """The masked kernel variant honors the [K, N] feasibility mask."""
    rng = np.random.default_rng(40 + seed)
    k, n = 16, 256
    demands = np.stack([
        rng.uniform(100, 4000, k), rng.uniform(0.5, 8, k), np.zeros(k)
    ], axis=-1).astype(np.float32)
    totals = np.stack([
        rng.uniform(4000, 64000, n), rng.uniform(8, 64, n)
    ], axis=-1).astype(np.float32)
    avail = np.concatenate([
        totals * rng.uniform(0.1, 1.0, (n, 1)).astype(np.float32),
        np.zeros((n, 1), np.float32),
    ], axis=-1)
    mask = rng.uniform(size=(k, n)) > 0.5

    # oracle: fold the mask into validity per job
    want_i = np.empty(k, dtype=np.int64)
    for a in range(k):
        _, wi = oracle(demands[a:a + 1], avail, totals, mask[a])
        want_i[a] = wi[0]
    got_v, got_i = best_node(
        jnp.asarray(demands), jnp.asarray(avail), jnp.asarray(totals),
        jnp.ones(n, bool), jnp.asarray(mask),
        block_jobs=8, block_nodes=128, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got_i), want_i)


@pytest.mark.parametrize("seed", range(3))
def test_pallas_backend_chunked_match_parity(seed):
    """chunked_match(use_pallas=True) reaches the same >=0.99 packing bar
    as the XLA backend (the matcher's `backend: pallas` path)."""
    from cook_tpu.ops import cpu_reference as ref
    from cook_tpu.ops.match import MatchProblem, chunked_match, greedy_match

    rng = np.random.default_rng(600 + seed)
    j, n = 256, 128
    demands = np.stack([
        rng.uniform(10, 500, j), rng.uniform(0.5, 8, j), np.zeros(j)
    ], axis=-1).astype(np.float32)
    totals = np.stack([
        rng.uniform(1000, 8000, n), rng.uniform(8, 64, n)
    ], axis=-1).astype(np.float32)
    avail = np.concatenate([
        totals * rng.uniform(0.3, 1.0, (n, 1)).astype(np.float32),
        np.zeros((n, 1), np.float32)], axis=-1)
    feasible = rng.uniform(size=(j, n)) > 0.1
    problem = MatchProblem(
        demands=jnp.asarray(demands), job_valid=jnp.ones(j, bool),
        avail=jnp.asarray(avail), totals=jnp.asarray(totals),
        node_valid=jnp.ones(n, bool), feasible=jnp.asarray(feasible))
    exact = np.asarray(greedy_match(problem).assignment)
    # kc is effectively 1, so the pallas backend converges in
    # O(nodes-to-fill) passes — each pass is one cheap fused sweep
    fast_r = chunked_match(problem, chunk=64, rounds=2, passes=12,
                           use_pallas=True)
    fast = np.asarray(fast_r.assignment)
    assert np.all(np.asarray(fast_r.new_avail) >= -1e-3)
    qe = ref.packing_quality(demands, exact)
    qf = ref.packing_quality(demands, fast)
    assert qf["num_placed"] >= 0.99 * qe["num_placed"]
    assert qf["cpus_placed"] >= 0.99 * qe["cpus_placed"]


def test_pallas_backend_through_scheduler_config():
    """`MatchConfig(backend="pallas")` drives a real scheduler match cycle
    end to end: every job lands, accounting matches the cluster state."""
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.entities import JobState, Pool
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from cook_tpu.scheduler.matcher import MatchConfig
    from tests.conftest import FakeClock, make_job

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    hosts = [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4000, cpus=8)
             for i in range(4)]
    cluster = MockCluster("m", hosts, clock=clock)
    scheduler = Scheduler(
        store, [cluster],
        SchedulerConfig(match=MatchConfig(
            chunk=16, backend="pallas", chunk_rounds=2, chunk_passes=12)))
    jobs = [make_job(user=f"u{i % 3}", mem=500, cpus=1) for i in range(12)]
    store.submit_jobs(jobs)
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == len(jobs)
    for job in jobs:
        assert store.jobs[job.uuid].state == JobState.RUNNING
