"""Hierarchical two-level matcher (ops/hierarchical.py): packing parity
vs the flat CPU reference across block counts, one fine-solve XLA program
across block counts (CompileObservatory-pinned), phantom-free mesh
padding, the QualityMonitor guard on degraded decompositions, and the
scheduler wiring (threshold trigger, CycleRecord fields, fallback
ladder)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cook_tpu.obs.compile_observatory import CompileObservatory
from cook_tpu.obs.quality_monitor import QualityMonitor
from cook_tpu.ops import cpu_reference as ref
from cook_tpu.ops.hierarchical import (
    HierParams,
    choose_nodes_per_block,
    hierarchical_match,
)
from cook_tpu.ops.match import MatchProblem
from cook_tpu.parallel.mesh import make_mesh
from tests.conftest import FakeClock
from tests.test_ops_parity import random_match_problem

# pinned packing-parity tolerance vs the flat np_greedy_match reference:
# the decomposition trades a bounded amount of packing quality for the
# block-batched schedule; a drop below this bar is a regression, not
# noise (tests below measure ~0.96-1.0 on these seeds)
HIER_EFF_TOLERANCE = 0.95


def dense_problem(j, n, seed=0):
    """Unconstrained seeded problem (bench.make_problem shape family)."""
    rng = np.random.default_rng(seed)
    demands = np.stack([
        rng.choice([512.0, 1024.0, 2048.0, 4096.0], j),
        rng.choice([0.5, 1.0, 2.0, 4.0], j),
        np.zeros(j),
    ], axis=-1).astype(np.float32)
    totals = np.stack([np.full(n, 65536.0), np.full(n, 32.0)],
                      axis=-1).astype(np.float32)
    frac = rng.uniform(0.2, 1.0, (n, 1)).astype(np.float32)
    avail = np.concatenate([totals * frac, np.zeros((n, 1), np.float32)],
                           axis=-1)
    return demands, avail, totals


def as_problem(demands, avail, totals, feasible=None):
    j, n = demands.shape[0], avail.shape[0]
    return MatchProblem(
        demands=jnp.asarray(demands), job_valid=jnp.ones(j, dtype=bool),
        avail=jnp.asarray(avail), totals=jnp.asarray(totals),
        node_valid=jnp.ones(n, dtype=bool),
        feasible=None if feasible is None else jnp.asarray(feasible),
    )


def assert_valid(demands, avail, assignment, feasible=None):
    """No oversubscribed node, no constraint-mask violation."""
    placed = assignment >= 0
    n = avail.shape[0]
    assert (assignment[placed] < n).all()
    use = np.zeros_like(avail, dtype=np.float64)
    np.add.at(use, assignment[placed],
              demands[placed].astype(np.float64)[:, :avail.shape[1]])
    assert (use <= avail.astype(np.float64) + 1e-3).all(), \
        "a node was oversubscribed"
    if feasible is not None:
        assert feasible[np.where(placed)[0], assignment[placed]].all()


def efficiency(demands, assignment, ref_assignment):
    q_dev = ref.packing_quality(demands, assignment)
    q_ref = ref.packing_quality(demands, ref_assignment)
    if not q_ref["cpus_placed"]:
        return 1.0
    return q_dev["cpus_placed"] / q_ref["cpus_placed"]


def test_choose_nodes_per_block_buckets():
    # tuned buckets: largest width keeping >= 8 blocks, fallback to >= 2
    assert choose_nodes_per_block(16384) == 1024
    assert choose_nodes_per_block(1024) == 128
    assert choose_nodes_per_block(256) == 128  # >= 2-block fallback
    assert choose_nodes_per_block(96) == 64    # smallest bucket floor
    assert choose_nodes_per_block(16384, override=512) == 512


@pytest.mark.parametrize("npb", [32, 64, 128])
def test_parity_across_block_counts(npb):
    """Property-style parity pin: hierarchical packing efficiency stays
    within HIER_EFF_TOLERANCE of the flat reference greedy, at several
    block decompositions of the same seeded problem."""
    demands, avail, totals = dense_problem(512, 256, seed=npb)
    problem = as_problem(demands, avail, totals)
    result, stats = hierarchical_match(
        problem, params=HierParams(nodes_per_block=npb, chunk=256, kc=32))
    a = np.asarray(result.assignment)
    assert_valid(demands, avail[:, :3], a)
    flat = ref.np_greedy_match(demands, avail[:, :3], totals)
    eff = efficiency(demands, a, flat)
    assert eff >= HIER_EFF_TOLERANCE, (npb, eff)
    assert stats["blocks"] == 256 // npb


def test_parity_with_constraint_mask():
    rng = np.random.default_rng(3)
    demands, avail, totals, feasible = random_match_problem(rng, j=256,
                                                           n=128)
    problem = as_problem(demands, avail, totals, feasible)
    result, _ = hierarchical_match(
        problem, params=HierParams(nodes_per_block=32, chunk=128, kc=32))
    a = np.asarray(result.assignment)
    assert_valid(demands, avail, a, feasible=feasible)
    flat = ref.np_greedy_match(demands, avail, totals,
                               feasible_mask=feasible)
    assert efficiency(demands, a, flat) >= HIER_EFF_TOLERANCE


def test_pallas_coarse_matches_xla_coarse():
    """The fused best_block coarse backend (interpret mode on CPU) is a
    drop-in for the masked XLA coarse pass on an unconstrained
    problem."""
    demands, avail, totals = dense_problem(256, 128, seed=9)
    problem = as_problem(demands, avail, totals)
    outs = {}
    for cb in ("xla", "pallas"):
        result, stats = hierarchical_match(
            problem, params=HierParams(nodes_per_block=32, chunk=128,
                                       kc=32, coarse_backend=cb))
        outs[cb] = np.asarray(result.assignment)
        assert stats["coarse_backend"] == cb
    flat = ref.np_greedy_match(demands, avail[:, :3], totals)
    for cb, a in outs.items():
        assert_valid(demands, avail[:, :3], a)
        assert efficiency(demands, a, flat) >= HIER_EFF_TOLERANCE, cb


def test_best_block_kernel_semantics():
    """best_block == argmax over blocks of (aggregate fit AND max-node
    gate AND valid) scored by the binpack fitness on aggregates."""
    from cook_tpu.ops.pallas_match import best_block

    rng = np.random.default_rng(4)
    k, b = 16, 8
    demands = rng.uniform(10, 500, (k, 3)).astype(np.float32)
    bsum = rng.uniform(100, 2000, (b, 3)).astype(np.float32)
    bmax = (bsum * rng.uniform(0.1, 1.0, (b, 3))).astype(np.float32)
    btot = (bsum[:, :2] * 1.5).astype(np.float32)
    valid = rng.uniform(size=b) > 0.2
    val, idx = best_block(jnp.asarray(demands), jnp.asarray(bsum),
                          jnp.asarray(bmax), jnp.asarray(btot),
                          jnp.asarray(valid), interpret=True)
    val, idx = np.asarray(val), np.asarray(idx)
    used0 = btot[:, 0] - bsum[:, 0]
    used1 = btot[:, 1] - bsum[:, 1]
    denom = np.maximum(btot, 1e-30)
    for ji in range(k):
        feas = ((bsum >= demands[ji]).all(axis=1)
                & (bmax >= demands[ji]).all(axis=1) & valid)
        fit = ((used0 + demands[ji, 0]) / denom[:, 0]
               + (used1 + demands[ji, 1]) / denom[:, 1]) * 0.5
        if not feas.any():
            assert idx[ji] == -1
            continue
        fit[~feas] = -np.inf
        assert idx[ji] == int(np.argmax(fit))
        np.testing.assert_allclose(val[ji], fit[idx[ji]], rtol=1e-5)


def test_refine_places_spilled_jobs():
    """Slot-cap overflow spills to the refinement round instead of
    silently dropping: with refinement on, the spilled jobs place."""
    demands, avail, totals = dense_problem(256, 128, seed=7)
    problem = as_problem(demands, avail, totals)
    # 16-slot blocks on a 256-job problem force heavy spill
    base = dict(nodes_per_block=32, jobs_per_block=16, chunk=16, kc=16)
    _, stats0 = hierarchical_match(
        problem, params=HierParams(refine_rounds=0, **base))
    assert stats0["spilled"] > 0
    result2, stats2 = hierarchical_match(
        problem, params=HierParams(refine_rounds=4, **base))
    assert stats2["placed"] > stats0["placed"]
    assert stats2["refine_placed"] > 0
    assert_valid(demands, avail[:, :3], np.asarray(result2.assignment))


def test_one_fine_program_across_block_counts():
    """The acceptance pin: >= 3 different real block counts (3, 5, 8 —
    none dividing into the next) pad onto the SAME fine batch shape via
    invalid_match_problem lanes, so the CompileObservatory sees exactly
    ONE match_fine XLA program, with the mesh engaged."""
    mesh = make_mesh()  # 8 virtual cpu devices (conftest)
    observatory = CompileObservatory()
    npb, slots = 32, 128
    for blocks in (3, 5, 8):
        n = blocks * npb
        demands, avail, totals = dense_problem(256, n, seed=blocks)
        problem = as_problem(demands, avail, totals)
        result, stats = hierarchical_match(
            problem,
            params=HierParams(nodes_per_block=npb, jobs_per_block=slots,
                              chunk=64, kc=32),
            mesh=mesh, observatory=observatory)
        assert stats["blocks"] == blocks
        assert stats["block_pad"] == 8
        a = np.asarray(result.assignment)
        assert_valid(demands, avail[:, :3], a)
        # zero phantom matches: every placement indexes a REAL node of a
        # REAL block — the invalid padding lanes contribute nothing
        placed = a[a >= 0]
        assert (placed < n).all()
        assert (a >= 0).sum() > 0
    stats = observatory.stats()
    assert stats["match_fine"]["programs"] == 1
    # the coarse pass shares one program across block counts too
    assert stats["match_coarse"]["programs"] == 1


def test_degraded_hierarchical_raises_quality_drift():
    """QualityMonitor guard: a degraded hierarchical solve (starved slot
    caps, no refinement — the failure mode of a bad tuned config) drops
    packing efficiency through the parity floor and surfaces
    quality-drift."""
    from types import SimpleNamespace

    demands, avail, totals = dense_problem(256, 128, seed=13)
    problem = as_problem(demands, avail, totals)
    result, stats = hierarchical_match(
        problem, params=HierParams(nodes_per_block=32, jobs_per_block=16,
                                   refine_rounds=0, chunk=16, kc=16))
    assert stats["spilled"] > 0  # genuinely degraded
    monitor = QualityMonitor(sample_every=1, floor=0.97)
    prepared = SimpleNamespace(problem=problem, nodes=None,
                               considerable=[object()] * 256,
                               feasible=None)
    ratio = monitor.observe_cycle(prepared, np.asarray(result.assignment),
                                  "xl-pool")
    assert ratio is not None and ratio < 0.97
    drifting = monitor.drifting_pools()
    assert "xl-pool" in drifting
    assert drifting["xl-pool"]["kind"] == "parity-floor"


# ------------------------------------------------------ scheduler wiring


def _scenario(match_config):
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.entities import Job, Pool, Resources
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    hosts = [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=32768.0,
                      cpus=16.0, pool="default") for i in range(64)]
    cluster = MockCluster("mock", hosts, clock=clock)
    scheduler = Scheduler(store, [cluster],
                          SchedulerConfig(match=match_config))
    rng = np.random.default_rng(5)
    jobs = [
        Job(uuid=f"j{i:04d}", user=f"u{i % 4}", pool="default", priority=50,
            resources=Resources(mem=float(rng.choice([512, 1024, 2048])),
                                cpus=float(rng.choice([1, 2]))),
            command="true")
        for i in range(300)
    ]
    store.submit_jobs(jobs)
    return store, scheduler


def _hier_config(**kw):
    from cook_tpu.scheduler.matcher import MatchConfig

    return MatchConfig(chunk=64, chunk_kc=32, quality_audit_every=0,
                       hierarchical_threshold=1,
                       hierarchical_nodes_per_block=16, **kw)


def test_match_cycle_hierarchical_threshold_and_record():
    """Above the threshold the serial cycle routes to the two-level
    matcher: jobs place, and the CycleRecord carries the hierarchical
    identity (backend label, block count, coarse/fine/refine walls,
    per-block stats)."""
    store, scheduler = _scenario(_hier_config())
    pool = store.pools["default"]
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) > 250
    record = scheduler.recorder.records(limit=1)[0]
    assert record.hierarchical
    assert record.backend.startswith("hier-")
    assert record.hier_blocks == 4  # 64 hosts / 16 per block
    assert set(record.hier_phases) == {"coarse_solve", "fine_solve",
                                       "refine"}
    assert record.hier_phases["coarse_solve"] > 0
    assert len(record.block_stats) == record.hier_blocks
    assert sum(b["jobs"] for b in record.block_stats) <= 300
    # the record round-trips to JSON with the new fields
    as_json = record.to_json()
    assert as_json["hierarchical"] and as_json["hier_blocks"] == 4


def test_match_cycle_below_threshold_stays_flat():
    config = _hier_config()
    config.hierarchical_threshold = 10**9  # never reached at this size
    store, scheduler = _scenario(config)
    outcome = scheduler.match_cycle(store.pools["default"])
    assert len(outcome.matched) > 250
    record = scheduler.recorder.records(limit=1)[0]
    assert not record.hierarchical
    assert not record.backend.startswith("hier-")


def test_batched_cycle_routes_hierarchical_pools():
    """match_cycle_all_pools must honor the threshold too: an
    over-threshold pool solves through the two-level path (its record
    carries the hierarchical identity) instead of riding the flat
    batched kernel."""
    store, scheduler = _scenario(_hier_config())
    outcomes = scheduler.match_cycle_all_pools()
    assert len(outcomes["default"].matched) > 250
    record = scheduler.recorder.records(limit=1)[0]
    assert record.batched and record.hierarchical
    assert record.backend.startswith("hier-")
    assert record.hier_blocks == 4


def test_pipelined_cycle_threads_hierarchical():
    store, scheduler = _scenario(_hier_config())
    outcomes = scheduler.match_cycle_pipelined()
    assert len(outcomes["default"].matched) > 250
    record = scheduler.recorder.records(limit=1)[0]
    assert record.pipelined and record.hierarchical
    assert record.backend.startswith("hier-")


def test_hierarchical_solve_error_rides_fallback_ladder():
    """A raising hierarchical solve degrades through the PR 7 ladder:
    the failing cycle re-solves on the CPU reference (no cycle lost) and
    the pool reports device-degraded until a probe succeeds."""
    from cook_tpu import faults

    store, scheduler = _scenario(_hier_config(device_fallback_cycles=2))
    pool = store.pools["default"]
    with faults.injected({"point": faults.DEVICE_SOLVE, "times": 1}):
        outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) > 250  # CPU fallback solved THIS cycle
    record = scheduler.recorder.records(limit=1)[0]
    assert record.backend == "cpu-fallback"
    assert scheduler.telemetry.device_fallbacks()  # degraded episode open
