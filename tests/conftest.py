"""Test configuration: force an 8-device virtual CPU mesh so sharding tests
run without TPU hardware (the driver separately dry-runs the multi-chip path
via __graft_entry__.dryrun_multichip)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment may pre-register an accelerator platform ahead of cpu
# (jax_platforms=axon,cpu); force pure-CPU for deterministic 8-device tests.
jax.config.update("jax_platforms", "cpu")

import atexit  # noqa: E402
import gc  # noqa: E402


def _jax_teardown_barrier():
    """Interpreter-teardown barrier for the intermittent SIGABRT
    ("terminate called without an active exception") the FULL tier-1
    run sometimes hits at exit — jax/XLA worker threads torn down while
    still holding work (pre-existing, reproduced identically on the
    seed commit; see docs/status.md).  Registered AFTER jax's import so
    it runs BEFORE jax's own atexit hooks (LIFO): clear the executable
    caches and collect while the runtime is still fully alive, so
    nothing is mid-flight when the backend unwinds."""
    try:
        jax.clear_caches()
        gc.collect()
    except Exception:  # noqa: BLE001 — a teardown helper must never
        # turn a green run red
        pass


atexit.register(_jax_teardown_barrier)

import pytest  # noqa: E402

from cook_tpu.models.entities import (  # noqa: E402
    Instance,
    Job,
    Pool,
    Resources,
    new_uuid,
)
from cook_tpu.models.store import JobStore  # noqa: E402


class FakeClock:
    def __init__(self, now_ms: int = 1_000_000):
        self.now_ms = now_ms

    def __call__(self) -> int:
        return self.now_ms

    def advance(self, ms: int) -> None:
        self.now_ms += ms


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def store(clock):
    s = JobStore(clock=clock)
    s.set_pool(Pool(name="default"))
    return s


def make_job(user="alice", pool="default", mem=100.0, cpus=1.0, gpus=0.0,
             priority=50, max_retries=1, resources=None, **kw) -> Job:
    return Job(
        uuid=new_uuid(),
        user=user,
        pool=pool,
        priority=priority,
        max_retries=max_retries,
        resources=resources or Resources(mem=mem, cpus=cpus, gpus=gpus),
        command="true",
        **kw,
    )


@pytest.fixture
def job_factory():
    return make_job
