"""Per-user launch rate limiting in the match cycle."""
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from tests.conftest import FakeClock, make_job


def test_user_launch_rate_limited():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m", [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=8000, cpus=32)
              for i in range(4)],
        clock=clock)
    scheduler = Scheduler(
        store, [cluster],
        SchedulerConfig(user_launch_rate_per_minute=60.0,
                        user_launch_burst=3.0),
    )
    jobs = [make_job(user="burster", mem=100, cpus=1) for _ in range(10)]
    store.submit_jobs(jobs)
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    # burst of 3 launches, the rest rate-limited
    assert len(outcome.matched) == 3
    # immediately rerunning: bucket empty, nothing launches
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == 0
    # tokens refill at 1/s but the bucket caps at the burst size (3)
    clock.advance(10_000)
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == 3
