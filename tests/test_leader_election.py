"""Networked leader election + journal replication, in-process tier.

Reference semantics under test: ZooKeeper-elected single leader with hot
standbys (mesos.clj:153-328) and Datomic as a replicated source of truth
that failover replays from (datomic.clj:45-127).  Here the coordination
point is the HTTP lease service (control/lease_server.py) and the
replication path is the standby's JournalFollower tailing the leader's
/replication feed — NO shared filesystem anywhere in these tests: every
process/node gets its own temp dir.

The whole-OS-process tier (spawned schedulers, SIGKILL the leader) lives
in tests/test_leader_http_failover.py.
"""
import shutil
import threading
import time

import requests

from cook_tpu.components import build_process, shutdown, start_leader_duties
from cook_tpu.control.leader import HttpLeaseElector
from cook_tpu.control.lease_server import LeaseServer, LeaseTable
from cook_tpu.control.replication import JournalFollower
from cook_tpu.models import persistence
from cook_tpu.rest.server import free_port
from cook_tpu.utils.config import Settings


class FakeMonoClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------- LeaseTable


def test_lease_table_grant_fence_expire():
    clock = FakeMonoClock()
    table = LeaseTable(clock=clock)
    a = table.acquire("g", "A", "http://a", ttl_s=10)
    assert a["acquired"] and a["epoch"] == 1
    # B cannot take a live lease
    assert not table.acquire("g", "B", "http://b", ttl_s=10)["acquired"]
    # A renews with its epoch; a stale epoch is fenced off
    assert table.heartbeat("g", "A", epoch=1, ttl_s=10)["ok"]
    assert not table.heartbeat("g", "A", epoch=0, ttl_s=10)["ok"]
    # expiry hands the lease to B, and A's next heartbeat is refused
    clock.t += 11
    b = table.acquire("g", "B", "http://b", ttl_s=10)
    assert b["acquired"] and b["epoch"] == 2
    hb = table.heartbeat("g", "A", epoch=1, ttl_s=10)
    assert not hb["ok"] and hb["leader"] == "B"
    assert table.current("g")["leader"] == "B"


def test_lease_table_release_and_reacquire_bumps_epoch():
    table = LeaseTable(clock=FakeMonoClock())
    a = table.acquire("g", "A", "", ttl_s=10)
    assert table.release("g", "A", epoch=a["epoch"])["released"]
    assert table.current("g")["leader"] is None
    # a stale-epoch release is a no-op
    b = table.acquire("g", "B", "", ttl_s=10)
    assert not table.release("g", "B", epoch=b["epoch"] - 1)["released"]
    assert table.current("g")["leader"] == "B"


# ------------------------------------------------------------ HttpLeaseElector


def test_http_elector_single_leader_over_http():
    server = LeaseServer().start()
    try:
        a = HttpLeaseElector(server.url, "cook", "A", ttl_s=5,
                             advertised_url="http://a:1")
        b = HttpLeaseElector(server.url, "cook", "B", ttl_s=5,
                             advertised_url="http://b:2")
        assert a.try_acquire()
        assert not b.try_acquire()
        assert b.current_leader() == "A"
        assert b.current_leader_url() == "http://a:1"
        assert a.heartbeat()
        a.release()
        assert b.try_acquire()
        assert a.current_leader() == "B"
        # A's heartbeat now carries a fenced-off epoch: definitive loss
        assert not a.heartbeat()
    finally:
        server.stop()


def test_http_elector_partition_grace_then_fail_fast():
    """Losing the lease SERVICE is indeterminate: the leader keeps leading
    for up to one TTL past its last confirmed renewal (a ZK session's
    grace), then fails fast — the service may have re-granted the lease."""
    server = LeaseServer().start()
    clock = FakeMonoClock()
    elector = HttpLeaseElector(server.url, "cook", "A", ttl_s=5,
                               timeout_s=0.5, clock=clock)
    assert elector.try_acquire()
    server.stop()  # partition: the service is gone
    clock.t += 3
    assert elector.heartbeat()  # within TTL of the last renewal: keep leading
    clock.t += 3
    assert not elector.heartbeat()  # past TTL: fail fast


def test_lease_server_restart_fences_old_leader_within_one_ttl():
    """The lease service is a single in-memory process (the deployment
    doc is honest about this): a restart erases the lease, and a standby
    can win the re-acquire race.  The bound under test: the OLD leader's
    next heartbeat after the restart is a DEFINITIVE loss (the restarted
    table holds no lease, or someone else's), so the dual-leader window
    is at most one heartbeat interval — never silent, never unbounded."""
    server = LeaseServer(port=0).start()
    port = server.port
    a = HttpLeaseElector(server.url, "cook", "A", ttl_s=5, timeout_s=1.0,
                         advertised_url="http://a:1")
    b = HttpLeaseElector(server.url, "cook", "B", ttl_s=5, timeout_s=1.0,
                         advertised_url="http://b:2")
    assert a.try_acquire()
    server.stop()
    # restart on the same address with an EMPTY table
    server2 = LeaseServer(port=port).start()
    try:
        # case 1: the standby wins the re-acquire race
        assert b.try_acquire()
        # old leader's next heartbeat: lease is B's (and A's epoch is from
        # the previous server incarnation) -> definitive loss, fail fast
        assert not a.heartbeat()

        # case 2: the sitting leader re-acquires first after a restart
        server2.table._leases.clear()
        assert a.try_acquire()
        # B's heartbeat (it thinks it leads from case 1) is fenced too
        assert not b.heartbeat()
    finally:
        server2.stop()


def test_lease_server_clamps_ttl_and_exact_paths():
    """A buggy/malicious acquire with a huge TTL must not lock the group
    to a dead member; path matching is exact."""
    import json as json_mod
    import urllib.error
    import urllib.request

    from cook_tpu.control.lease_server import MAX_TTL_S

    clock = FakeMonoClock()
    server = LeaseServer(clock=clock).start()
    try:
        def post(path, payload):
            req = urllib.request.Request(
                server.url + path, data=json_mod.dumps(payload).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req, timeout=2) as r:
                return json_mod.loads(r.read())

        resp = post("/acquire", {"group": "g", "member": "A",
                                 "ttl_s": 1e9})
        assert resp["acquired"]
        # server-side clamp: the lease lapses after MAX_TTL_S, not 1e9 s
        clock.t += MAX_TTL_S + 1
        resp = post("/acquire", {"group": "g", "member": "B", "ttl_s": 10})
        assert resp["acquired"], "huge client TTL locked the group"

        # exact path match: /leaderfoo is not /leader
        req = urllib.request.Request(server.url + "/leaderfoo")
        try:
            urllib.request.urlopen(req, timeout=2)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404

        # malformed ttl is a 400, not a 500
        req = urllib.request.Request(
            server.url + "/acquire",
            data=json_mod.dumps({"group": "g", "member": "C",
                                 "ttl_s": "bogus"}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            urllib.request.urlopen(req, timeout=2)
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        server.stop()


# ----------------------------------------------- standby replication/failover


def _settings(port, data_dir, lease_url, ttl=3.0):
    return Settings(
        port=port, data_dir=data_dir,
        leader_endpoint=lease_url, leader_ttl_s=ttl,
        clusters=[{
            "kind": "mock", "name": "m1",
            "hosts": [{"node_id": "h0", "mem": 4000, "cpus": 8}],
        }],
        pools=[{"name": "default"}],
        rank_interval_s=3600, match_interval_s=3600,
    )


def test_standby_replicates_and_survives_leader_disk_loss(tmp_path):
    """The VERDICT-r3 acceptance shape: two schedulers, two separate data
    dirs, no shared filesystem; the standby replicates over HTTP; the
    leader dies AND ITS DATA DIR IS DELETED; the standby promotes with
    the full state."""
    lease = LeaseServer().start()
    dir1, dir2 = str(tmp_path / "node1"), str(tmp_path / "node2")
    h = {"X-Cook-Requesting-User": "u"}
    p1 = p2 = None
    try:
        s1 = _settings(free_port(), dir1, lease.url)
        p1 = build_process(s1)
        start_leader_duties(p1, block=False, on_loss=lambda: None)
        assert p1.is_leader()
        url1 = f"http://127.0.0.1:{s1.port}"
        uuids = [f"f0000000-0000-0000-0000-00000000001{i}" for i in range(3)]
        r = requests.post(f"{url1}/jobs", json={"jobs": [
            {"command": "x", "mem": 100, "cpus": 1, "uuid": u}
            for u in uuids
        ]}, headers=h)
        assert r.status_code == 201

        # standby comes up with ITS OWN empty data dir and replicates
        s2 = _settings(free_port(), dir2, lease.url)
        p2 = build_process(s2)
        standby = threading.Thread(
            target=start_leader_duties, args=(p2,),
            kwargs={"block": False, "on_loss": lambda: None}, daemon=True)
        standby.start()
        deadline = time.time() + 15
        while time.time() < deadline and uuids[0] not in p2.store.jobs:
            time.sleep(0.1)
        assert uuids[0] in p2.store.jobs, "standby never replicated"
        # standby REST serves the replicated state read-locally, and
        # points writes at the leader
        url2 = f"http://127.0.0.1:{s2.port}"
        r = requests.get(f"{url2}/jobs/{uuids[1]}", headers=h)
        assert r.status_code == 200
        assert not p2.is_leader()

        # a post-replication write also flows through
        extra = "f0000000-0000-0000-0000-0000000000ff"
        assert requests.post(f"{url1}/jobs", json={"jobs": [
            {"command": "y", "mem": 100, "cpus": 1, "uuid": extra},
        ]}, headers=h).status_code == 201
        deadline = time.time() + 15
        while time.time() < deadline and extra not in p2.store.jobs:
            time.sleep(0.1)
        assert extra in p2.store.jobs

        # leader dies; its disk burns
        shutdown(p1)
        p1 = None
        shutil.rmtree(dir1)

        standby.join(timeout=30)
        assert p2.is_leader(), "standby never promoted"
        assert all(u in p2.store.jobs for u in uuids + [extra])
        r = requests.get(f"{url2}/jobs/{extra}", headers=h)
        assert r.status_code == 200
        # promotion flipped REST to leader mode
        assert requests.get(f"{url2}/debug").json()["leader"] is True
        # and the standby's own disk now carries the state (a third node
        # could recover from it)
        recovered = persistence.recover(dir2)
        assert recovered is not None
        assert all(u in recovered.jobs for u in uuids + [extra])
    finally:
        for p in (p1, p2):
            if p is not None:
                shutdown(p)
        lease.stop()


def test_promoted_standby_schedules_replicated_job_without_new_writes(
        tmp_path):
    """VERDICT-r4 regression: replicated events must reach the columnar
    rank index.  A job that arrived on the standby ONLY via replication
    must be schedulable by the very first rank+match cycles after
    promotion — with no REST write in between to paper over a stale
    index."""
    from cook_tpu.models.entities import JobState

    lease = LeaseServer().start()
    p1 = p2 = None
    h = {"X-Cook-Requesting-User": "u"}
    uuid = "f0000000-0000-0000-0000-000000000031"
    try:
        s1 = _settings(free_port(), str(tmp_path / "n1"), lease.url)
        p1 = build_process(s1)
        start_leader_duties(p1, block=False, on_loss=lambda: None)
        assert p1.is_leader()
        # leader intentionally never runs a match (intervals are 3600s):
        # the job must reach the standby WAITING
        assert requests.post(f"http://127.0.0.1:{s1.port}/jobs", json={
            "jobs": [{"command": "x", "mem": 100, "cpus": 1, "uuid": uuid}],
        }, headers=h).status_code == 201

        s2 = _settings(free_port(), str(tmp_path / "n2"), lease.url)
        p2 = build_process(s2)
        standby = threading.Thread(
            target=start_leader_duties, args=(p2,),
            kwargs={"block": False, "on_loss": lambda: None}, daemon=True)
        standby.start()
        deadline = time.time() + 15
        while time.time() < deadline and uuid not in p2.store.jobs:
            time.sleep(0.1)
        assert uuid in p2.store.jobs, "standby never replicated"
        # the replicated event fan-out kept the standby's columnar index
        # current the whole time — not just rebuilt at promotion
        assert p2.scheduler.columnar.consistent_with_store()

        shutdown(p1)
        p1 = None
        standby.join(timeout=30)
        assert p2.is_leader(), "standby never promoted"

        # first cycles after promotion, no intervening writes
        pool = p2.store.pools["default"]
        p2.scheduler.rank_cycle(pool)
        p2.scheduler.match_cycle(pool)
        assert p2.store.jobs[uuid].state == JobState.RUNNING
        insts = p2.store.job_instances(uuid)
        assert insts and insts[0].hostname == "h0"
    finally:
        for p in (p1, p2):
            if p is not None:
                shutdown(p)
        lease.stop()


def test_follower_bootstraps_via_snapshot_when_behind_window(tmp_path):
    """A leader that itself recovered from disk has an EMPTY in-memory
    event window but a non-zero seq: a fresh follower must be told
    snapshot_required and bootstrap via /replication/snapshot."""
    lease = LeaseServer().start()
    dir1, dir2 = str(tmp_path / "node1"), str(tmp_path / "node2")
    h = {"X-Cook-Requesting-User": "u"}
    uuid = "f0000000-0000-0000-0000-000000000021"
    # generation 1 writes and dies
    s1 = _settings(free_port(), dir1, lease.url)
    p1 = build_process(s1)
    start_leader_duties(p1, block=False, on_loss=lambda: None)
    assert requests.post(f"http://127.0.0.1:{s1.port}/jobs", json={"jobs": [
        {"command": "x", "mem": 100, "cpus": 1, "uuid": uuid},
    ]}, headers=h).status_code == 201
    shutdown(p1)

    # generation 2 recovers from disk (empty event window, seq > 0)
    s1b = _settings(free_port(), dir1, lease.url)
    p1b = build_process(s1b)
    p2 = None
    try:
        start_leader_duties(p1b, block=False, on_loss=lambda: None)
        assert uuid in p1b.store.jobs
        # the in-memory window no longer reaches back to the job events
        # (recovery replays from disk without re-emitting them), so a
        # follower at seq 0 has a genuine gap to cross
        events = p1b.store.events_since(0)
        assert all(e.kind != "job/created" for e in events)

        s2 = _settings(free_port(), dir2, lease.url)
        p2 = build_process(s2)
        follower = JournalFollower(
            p2.store,
            leader_url_fn=lambda: f"http://127.0.0.1:{s1b.port}",
            data_dir=dir2, journal=p2.journal)
        follower.sync_once()
        assert follower.full_resyncs == 1
        assert uuid in p2.store.jobs
        assert p2.store.last_seq() == p1b.store.last_seq()
        # the resync wrote a local snapshot: a cold recover of dir2 works
        recovered = persistence.recover(dir2)
        assert recovered is not None and uuid in recovered.jobs
    finally:
        shutdown(p1b)
        if p2 is not None:
            shutdown(p2)
        lease.stop()


def test_replication_endpoints_admin_gated(tmp_path):
    s = _settings(free_port(), str(tmp_path / "d"), "")
    s.leader_endpoint = ""  # plain single node
    p = build_process(s)
    try:
        url = f"http://127.0.0.1:{s.port}"
        for path in ("/replication/journal", "/replication/snapshot"):
            r = requests.get(f"{url}{path}",
                             headers={"X-Cook-Requesting-User": "mallory"})
            assert r.status_code == 403
            r = requests.get(f"{url}{path}",
                             headers={"X-Cook-Requesting-User": "admin"})
            assert r.status_code == 200
    finally:
        shutdown(p)
