"""Networked leader election + journal replication, in-process tier.

Reference semantics under test: ZooKeeper-elected single leader with hot
standbys (mesos.clj:153-328) and Datomic as a replicated source of truth
that failover replays from (datomic.clj:45-127).  Here the coordination
point is the HTTP lease service (control/lease_server.py) and the
replication path is the standby's JournalFollower tailing the leader's
/replication feed — NO shared filesystem anywhere in these tests: every
process/node gets its own temp dir.

The whole-OS-process tier (spawned schedulers, SIGKILL the leader) lives
in tests/test_leader_http_failover.py.
"""
import shutil
import threading
import time

import requests

from cook_tpu.components import build_process, shutdown, start_leader_duties
from cook_tpu.control.leader import HttpLeaseElector
from cook_tpu.control.lease_server import LeaseServer, LeaseTable
from cook_tpu.control.replication import JournalFollower
from cook_tpu.models import persistence
from cook_tpu.rest.server import free_port
from cook_tpu.utils.config import Settings


class FakeMonoClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------- LeaseTable


def test_lease_table_grant_fence_expire():
    clock = FakeMonoClock()
    table = LeaseTable(clock=clock)
    a = table.acquire("g", "A", "http://a", ttl_s=10)
    assert a["acquired"] and a["epoch"] == 1
    # B cannot take a live lease
    assert not table.acquire("g", "B", "http://b", ttl_s=10)["acquired"]
    # A renews with its epoch; a stale epoch is fenced off
    assert table.heartbeat("g", "A", epoch=1, ttl_s=10)["ok"]
    assert not table.heartbeat("g", "A", epoch=0, ttl_s=10)["ok"]
    # expiry hands the lease to B, and A's next heartbeat is refused
    clock.t += 11
    b = table.acquire("g", "B", "http://b", ttl_s=10)
    assert b["acquired"] and b["epoch"] == 2
    hb = table.heartbeat("g", "A", epoch=1, ttl_s=10)
    assert not hb["ok"] and hb["leader"] == "B"
    assert table.current("g")["leader"] == "B"


def test_lease_table_release_and_reacquire_bumps_epoch():
    table = LeaseTable(clock=FakeMonoClock())
    a = table.acquire("g", "A", "", ttl_s=10)
    assert table.release("g", "A", epoch=a["epoch"])["released"]
    assert table.current("g")["leader"] is None
    # a stale-epoch release is a no-op
    b = table.acquire("g", "B", "", ttl_s=10)
    assert not table.release("g", "B", epoch=b["epoch"] - 1)["released"]
    assert table.current("g")["leader"] == "B"


# ------------------------------------------------------------ HttpLeaseElector


def test_http_elector_single_leader_over_http():
    server = LeaseServer().start()
    try:
        a = HttpLeaseElector(server.url, "cook", "A", ttl_s=5,
                             advertised_url="http://a:1")
        b = HttpLeaseElector(server.url, "cook", "B", ttl_s=5,
                             advertised_url="http://b:2")
        assert a.try_acquire()
        assert not b.try_acquire()
        assert b.current_leader() == "A"
        assert b.current_leader_url() == "http://a:1"
        assert a.heartbeat()
        a.release()
        assert b.try_acquire()
        assert a.current_leader() == "B"
        # A's heartbeat now carries a fenced-off epoch: definitive loss
        assert not a.heartbeat()
    finally:
        server.stop()


def test_http_elector_partition_grace_then_fail_fast():
    """Losing the lease SERVICE is indeterminate: the leader keeps leading
    for up to one TTL past its last confirmed renewal (a ZK session's
    grace), then fails fast — the service may have re-granted the lease."""
    server = LeaseServer().start()
    clock = FakeMonoClock()
    elector = HttpLeaseElector(server.url, "cook", "A", ttl_s=5,
                               timeout_s=0.5, clock=clock)
    assert elector.try_acquire()
    server.stop()  # partition: the service is gone
    clock.t += 3
    assert elector.heartbeat()  # within TTL of the last renewal: keep leading
    clock.t += 3
    assert not elector.heartbeat()  # past TTL: fail fast


# ----------------------------------------------- standby replication/failover


def _settings(port, data_dir, lease_url, ttl=3.0):
    return Settings(
        port=port, data_dir=data_dir,
        leader_endpoint=lease_url, leader_ttl_s=ttl,
        clusters=[{
            "kind": "mock", "name": "m1",
            "hosts": [{"node_id": "h0", "mem": 4000, "cpus": 8}],
        }],
        pools=[{"name": "default"}],
        rank_interval_s=3600, match_interval_s=3600,
    )


def test_standby_replicates_and_survives_leader_disk_loss(tmp_path):
    """The VERDICT-r3 acceptance shape: two schedulers, two separate data
    dirs, no shared filesystem; the standby replicates over HTTP; the
    leader dies AND ITS DATA DIR IS DELETED; the standby promotes with
    the full state."""
    lease = LeaseServer().start()
    dir1, dir2 = str(tmp_path / "node1"), str(tmp_path / "node2")
    h = {"X-Cook-Requesting-User": "u"}
    p1 = p2 = None
    try:
        s1 = _settings(free_port(), dir1, lease.url)
        p1 = build_process(s1)
        start_leader_duties(p1, block=False, on_loss=lambda: None)
        assert p1.is_leader()
        url1 = f"http://127.0.0.1:{s1.port}"
        uuids = [f"f0000000-0000-0000-0000-00000000001{i}" for i in range(3)]
        r = requests.post(f"{url1}/jobs", json={"jobs": [
            {"command": "x", "mem": 100, "cpus": 1, "uuid": u}
            for u in uuids
        ]}, headers=h)
        assert r.status_code == 201

        # standby comes up with ITS OWN empty data dir and replicates
        s2 = _settings(free_port(), dir2, lease.url)
        p2 = build_process(s2)
        standby = threading.Thread(
            target=start_leader_duties, args=(p2,),
            kwargs={"block": False, "on_loss": lambda: None}, daemon=True)
        standby.start()
        deadline = time.time() + 15
        while time.time() < deadline and uuids[0] not in p2.store.jobs:
            time.sleep(0.1)
        assert uuids[0] in p2.store.jobs, "standby never replicated"
        # standby REST serves the replicated state read-locally, and
        # points writes at the leader
        url2 = f"http://127.0.0.1:{s2.port}"
        r = requests.get(f"{url2}/jobs/{uuids[1]}", headers=h)
        assert r.status_code == 200
        assert not p2.is_leader()

        # a post-replication write also flows through
        extra = "f0000000-0000-0000-0000-0000000000ff"
        assert requests.post(f"{url1}/jobs", json={"jobs": [
            {"command": "y", "mem": 100, "cpus": 1, "uuid": extra},
        ]}, headers=h).status_code == 201
        deadline = time.time() + 15
        while time.time() < deadline and extra not in p2.store.jobs:
            time.sleep(0.1)
        assert extra in p2.store.jobs

        # leader dies; its disk burns
        shutdown(p1)
        p1 = None
        shutil.rmtree(dir1)

        standby.join(timeout=30)
        assert p2.is_leader(), "standby never promoted"
        assert all(u in p2.store.jobs for u in uuids + [extra])
        r = requests.get(f"{url2}/jobs/{extra}", headers=h)
        assert r.status_code == 200
        # promotion flipped REST to leader mode
        assert requests.get(f"{url2}/debug").json()["leader"] is True
        # and the standby's own disk now carries the state (a third node
        # could recover from it)
        recovered = persistence.recover(dir2)
        assert recovered is not None
        assert all(u in recovered.jobs for u in uuids + [extra])
    finally:
        for p in (p1, p2):
            if p is not None:
                shutdown(p)
        lease.stop()


def test_follower_bootstraps_via_snapshot_when_behind_window(tmp_path):
    """A leader that itself recovered from disk has an EMPTY in-memory
    event window but a non-zero seq: a fresh follower must be told
    snapshot_required and bootstrap via /replication/snapshot."""
    lease = LeaseServer().start()
    dir1, dir2 = str(tmp_path / "node1"), str(tmp_path / "node2")
    h = {"X-Cook-Requesting-User": "u"}
    uuid = "f0000000-0000-0000-0000-000000000021"
    # generation 1 writes and dies
    s1 = _settings(free_port(), dir1, lease.url)
    p1 = build_process(s1)
    start_leader_duties(p1, block=False, on_loss=lambda: None)
    assert requests.post(f"http://127.0.0.1:{s1.port}/jobs", json={"jobs": [
        {"command": "x", "mem": 100, "cpus": 1, "uuid": uuid},
    ]}, headers=h).status_code == 201
    shutdown(p1)

    # generation 2 recovers from disk (empty event window, seq > 0)
    s1b = _settings(free_port(), dir1, lease.url)
    p1b = build_process(s1b)
    p2 = None
    try:
        start_leader_duties(p1b, block=False, on_loss=lambda: None)
        assert uuid in p1b.store.jobs
        # the in-memory window no longer reaches back to the job events
        # (recovery replays from disk without re-emitting them), so a
        # follower at seq 0 has a genuine gap to cross
        events = p1b.store.events_since(0)
        assert all(e.kind != "job/created" for e in events)

        s2 = _settings(free_port(), dir2, lease.url)
        p2 = build_process(s2)
        follower = JournalFollower(
            p2.store,
            leader_url_fn=lambda: f"http://127.0.0.1:{s1b.port}",
            data_dir=dir2, journal=p2.journal)
        follower.sync_once()
        assert follower.full_resyncs == 1
        assert uuid in p2.store.jobs
        assert p2.store.last_seq() == p1b.store.last_seq()
        # the resync wrote a local snapshot: a cold recover of dir2 works
        recovered = persistence.recover(dir2)
        assert recovered is not None and uuid in recovered.jobs
    finally:
        shutdown(p1b)
        if p2 is not None:
            shutdown(p2)
        lease.stop()


def test_replication_endpoints_admin_gated(tmp_path):
    s = _settings(free_port(), str(tmp_path / "d"), "")
    s.leader_endpoint = ""  # plain single node
    p = build_process(s)
    try:
        url = f"http://127.0.0.1:{s.port}"
        for path in ("/replication/journal", "/replication/snapshot"):
            r = requests.get(f"{url}{path}",
                             headers={"X-Cook-Requesting-User": "mallory"})
            assert r.status_code == 403
            r = requests.get(f"{url}{path}",
                             headers={"X-Cook-Requesting-User": "admin"})
            assert r.status_code == 200
    finally:
        shutdown(p)
