"""The smoke-bench tier and the bench-regression gate: every PR runs the
real kernels at tiny sizes and validates the BENCH record/gate machinery
(tools/bench_gate.py)."""
import importlib.util
import json
import pathlib
import sys

import pytest

REPO = pathlib.Path(__file__).parent.parent
sys.path.insert(0, str(REPO / "tools"))

import bench_gate  # noqa: E402


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.fixture(scope="module")
def smoke_record(tmp_path_factory):
    """One real smoke run for the whole module (compiles three tiny
    kernels once)."""
    bench = _load_bench()
    out = tmp_path_factory.mktemp("bench") / "BENCH_rsmoke.json"
    # pipeline=False: the pipelined-vs-serial tier costs ~45 s and has
    # its own functional coverage in tests/test_pipeline.py
    record = bench.smoke_main(out=str(out), pipeline=False)
    return record, out, bench


def test_smoke_emits_structured_record(smoke_record):
    record, out, _ = smoke_record
    assert out.exists()
    on_disk = json.loads(out.read_text())
    assert on_disk["schema"] == "cook-bench/v1"
    assert on_disk["mode"] == "smoke"
    assert set(on_disk["phases"]) == {"match", "dru", "rebalance",
                                      "elastic_plan", "control_plane",
                                      "control_plane_sharded",
                                      "control_plane_mp",
                                      "match_xl", "match_xl_coarse",
                                      "match_xl_fine", "match_xl_refine",
                                      "match_xxl",
                                      "match_xxl_super_coarse",
                                      "match_xxl_coarse",
                                      "match_xxl_fine",
                                      "match_xxl_refine",
                                      "speculation", "match_resident",
                                      "match_resident_cold",
                                      "rebalance_resident",
                                      "rebalance_resident_cold",
                                      "elastic_resident",
                                      "elastic_resident_cold", "gang"}
    # every record and every phase carries the resolved JAX backend —
    # the label bench_gate uses to refuse cross-backend comparisons
    assert on_disk["backend"] == "cpu"
    for phase in on_disk["phases"].values():
        assert phase["p50_ms"] > 0
        assert phase["backend"] == "cpu"
    # data-plane byte stamps (obs/data_plane.py): the kernel phases
    # carry deterministic h2d/d2h byte columns — the one signal
    # bench_gate can diff even across a CPU-fallback/accelerator pair
    for phase in ("match", "dru", "rebalance", "match_xl"):
        assert on_disk["phases"][phase]["h2d_bytes"] > 0, phase
        assert on_disk["phases"][phase]["d2h_bytes"] > 0, phase
    assert on_disk["headline"]["unit"] == "ms"
    assert record["phases"]["match"]["jobs"] == 1000
    # the control-plane phase gates commit-ack p50 and records the p99
    # the sharding work (ROADMAP item 2) is judged against
    control = record["phases"]["control_plane"]
    assert control["commit_ack_p99_ms"] >= control["p50_ms"]
    assert control["errors"] == 0 and control["submits"] > 0
    # the sharded phase (cook_tpu/shard/) records the 4-shard run AND
    # its concurrency-matched single-shard baseline on the same trace,
    # so the partitioning comparison is self-contained in the record
    sharded = record["phases"]["control_plane_sharded"]
    assert sharded["shards"] == 4
    assert sharded["errors"] == 0 and sharded["submits"] > 0
    assert set(sharded["per_shard"]) == {"0", "1", "2", "3"}
    assert sharded["single_shard"]["achieved_rps"] > 0
    assert sharded["rps_speedup_vs_single"] > 0
    # the multi-process phase (cook_tpu/mp/) records the worker count,
    # the speedup vs the in-process sharded baseline, and the `cores`
    # stamp that makes a 1-core record honest (recorded, not gated:
    # the >=2.5x target needs real cores — see observability.md)
    mp = record["phases"]["control_plane_mp"]
    assert mp["errors"] == 0 and mp["submits"] > 0
    assert mp["groups"] >= 2 and mp["cores"] >= 1
    assert mp["rps_speedup_vs_sharded"] > 0
    assert set(mp["per_worker"]) and mp["sharded_baseline"]["achieved_rps"] > 0


def test_smoke_match_holds_packing_parity(smoke_record):
    # the smoke shape is saturated on purpose; the chunked config must
    # still match the CPU greedy (kc=32/rounds=3/passes=3 -> eff 1.0,
    # see bench.bench_smoke) — a drop here is a real matcher regression
    record, _, _ = smoke_record
    assert record["phases"]["match"]["packing_eff"] >= 0.99


def test_smoke_match_xl_tier(smoke_record):
    """The hierarchical match_xl smoke tier: blocks engaged, per-phase
    (coarse/fine/refine) p50s recorded for the gate, packing parity
    within the pinned hierarchical tolerance."""
    record, _, _ = smoke_record
    xl = record["phases"]["match_xl"]
    assert xl["jobs"] == 2000 and xl["nodes"] == 256
    assert xl["blocks"] >= 2
    assert xl["packing_eff"] >= 0.95
    for phase in ("match_xl_coarse", "match_xl_fine"):
        assert record["phases"][phase]["p50_ms"] > 0


def test_smoke_match_resident_tier(smoke_record):
    """The device-residency tier: warm delta cycles must move >= 90%
    fewer node-encode + job-feasibility H2D bytes than the cold rebuild
    (the ISSUE-13 acceptance bar, judged on the PR 11 TransferLedger
    stamps), and both phases carry the gate-enforced byte columns."""
    record, _, _ = smoke_record
    warm = record["phases"]["match_resident"]
    cold = record["phases"]["match_resident_cold"]
    assert warm["warm_cycles"] == 3
    assert warm["h2d_bytes"] > 0 and cold["h2d_bytes"] > 0
    per_warm_encode = warm["encode_h2d_bytes"] / warm["warm_cycles"]
    assert per_warm_encode <= 0.1 * cold["encode_h2d_bytes"], (
        warm, cold)
    assert warm["encode_reduction"] >= 0.9


def test_smoke_speculation_tier(smoke_record):
    """The speculation phase: the completion-heavy A/B must show cycles
    served from speculation (the >= 0.2 ISSUE-10 bar) and a pre-launch
    p50 below the non-speculative baseline's."""
    record, _, _ = smoke_record
    spec = record["phases"]["speculation"]
    assert spec["hit_fraction"] >= 0.2
    assert spec["p50_ms"] < spec["baseline_p50_ms"]
    assert spec["cycles"] > 0


def test_smoke_gang_tier(smoke_record):
    """The gang phase: on the seeded gang/topology trace every gang
    must fully place, assembly must be total (the one-block rule holds),
    and the gated p50 is the deterministic virtual-ms admission wait."""
    record, _, _ = smoke_record
    gang = record["phases"]["gang"]
    assert gang["placed_fraction"] == 1.0
    assert gang["assembled_share"] == 1.0
    assert gang["block_spread"] == 1.0
    assert gang["gangs"] > 0
    assert gang["p50_ms"] > 0


def test_next_phase_record_path_skips_driver_rounds(tmp_path):
    bench = _load_bench()
    (tmp_path / "BENCH_r05.json").write_text("{}")
    (tmp_path / "BENCH_r07_phases.json").write_text("{}")
    assert bench._next_phase_record_path(str(tmp_path)).endswith(
        "BENCH_r08_phases.json")


def make_record(path, mode="smoke", platform="cpu", **phases):
    payload = {
        "schema": "cook-bench/v1", "mode": mode, "platform": platform,
        # a dict rides through as the phase's full info (mp phases carry
        # cores + speedup columns); a bare number is just the p50
        "phases": {name: (dict(info) if isinstance(info, dict)
                          else {"p50_ms": info})
                   for name, info in phases.items()},
    }
    path.write_text(json.dumps(payload))
    return str(path)


class TestBenchGate:
    def test_pass_within_threshold(self, tmp_path, capsys):
        old = make_record(tmp_path / "a.json", match=10.0, dru=2.0)
        new = make_record(tmp_path / "b.json", match=11.0, dru=2.1)
        assert bench_gate.main([old, new, "--threshold", "0.2"]) == 0
        assert "PASS" in capsys.readouterr().out

    def test_injected_regression_fails(self, tmp_path, capsys):
        """Acceptance: the gate exits non-zero on a synthetic regression."""
        old = make_record(tmp_path / "a.json", match=10.0, dru=2.0)
        new = make_record(tmp_path / "b.json", match=25.0, dru=2.0)
        assert bench_gate.main([old, new]) == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "match" in out

    def test_speedup_never_fails(self, tmp_path):
        old = make_record(tmp_path / "a.json", match=20.0)
        new = make_record(tmp_path / "b.json", match=5.0)
        assert bench_gate.main([old, new]) == 0

    def test_tiny_phase_jitter_inside_min_delta_passes(self, tmp_path,
                                                       capsys):
        # +50% on a 2 ms phase is inside OS scheduler jitter on a loaded
        # box; the absolute --min-delta-ms floor keeps it from flapping
        old = make_record(tmp_path / "a.json", dru=2.0)
        new = make_record(tmp_path / "b.json", dru=3.0)
        assert bench_gate.main([old, new]) == 0
        assert "within min-delta" in capsys.readouterr().out
        # but an explicit zero floor restores the pure relative gate
        assert bench_gate.main([old, new, "--min-delta-ms", "0"]) == 1

    def test_platform_mismatch_not_compared(self, tmp_path, capsys):
        # a CPU-fallback round must not "regress" against a TPU round
        old = make_record(tmp_path / "a.json", platform="tpu", match=0.5)
        new = make_record(tmp_path / "b.json", platform="cpu", match=800.0)
        assert bench_gate.main([old, new]) == 0
        assert "nothing to compare" in capsys.readouterr().out

    def test_smoke_singleton_does_not_shadow_full_rounds(self, tmp_path):
        # BENCH_rsmoke.json is a fixed overwritten name that sorts after
        # the numeric rounds; its singleton family must not disable the
        # full-round comparison
        make_record(tmp_path / "BENCH_r01_phases.json", mode="full",
                    match=100.0)
        make_record(tmp_path / "BENCH_r02_phases.json", mode="full",
                    match=300.0)
        make_record(tmp_path / "BENCH_rsmoke.json", mode="smoke", match=5.0)
        assert bench_gate.main(["--dir", str(tmp_path)]) == 1

    def test_comparable_ancestor_found_behind_mismatch(self, tmp_path):
        a = make_record(tmp_path / "a.json", platform="cpu", match=10.0)
        b = make_record(tmp_path / "b.json", platform="tpu", match=0.5)
        c = make_record(tmp_path / "c.json", platform="cpu", match=30.0)
        assert bench_gate.main([a, b, c]) == 1

    def test_driver_wrapper_records_skipped(self, tmp_path):
        # the round driver's BENCH_r{NN}.json wrappers carry no phases;
        # the gate must ignore them, not crash or compare garbage
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(
            {"n": 1, "cmd": "python bench.py", "rc": 0,
             "parsed": {"value": 800.0}}))
        old = make_record(tmp_path / "BENCH_r02_phases.json", match=10.0)
        assert bench_gate.main(["--dir", str(tmp_path)]) == 0

    def test_dir_glob_orders_by_round(self, tmp_path):
        make_record(tmp_path / "BENCH_r01_phases.json", match=10.0)
        make_record(tmp_path / "BENCH_r02_phases.json", match=50.0)
        assert bench_gate.main(["--dir", str(tmp_path)]) == 1
        # newest round is fine again -> pass (compared against r02)
        make_record(tmp_path / "BENCH_r03_phases.json", match=50.0)
        assert bench_gate.main(["--dir", str(tmp_path)]) == 0

    def test_missing_phase_fails_gate(self, tmp_path, capsys):
        # a phase vanishing from the new record must not read as "no
        # regression" — it could hide an arbitrarily large one
        old = make_record(tmp_path / "a.json", match=10.0, dru=2.0)
        new = make_record(tmp_path / "b.json", match=10.0)
        assert bench_gate.main([old, new]) == 1
        assert "missing from the new record" in capsys.readouterr().out

    def test_smoke_rotation_gives_gate_a_pair(self, tmp_path):
        """The documented CI workflow (`bench.py --smoke` then
        `bench_gate.py`) must actually gate: the fixed smoke name
        rotates to BENCH_rsmoke_prev.json instead of erasing the
        baseline."""
        import os

        bench = _load_bench()
        fast = {"schema": "cook-bench/v1", "mode": "smoke",
                "platform": "cpu", "phases": {"match": {"p50_ms": 5.0}}}
        slow = {**fast, "phases": {"match": {"p50_ms": 50.0}}}
        bench.write_bench_record(dict(fast), root=str(tmp_path))
        bench.write_bench_record(dict(slow), root=str(tmp_path))
        assert (tmp_path / "BENCH_rsmoke_prev.json").exists()
        os.utime(tmp_path / "BENCH_rsmoke.json")  # ensure newer mtime
        assert bench_gate.main(["--dir", str(tmp_path)]) == 1

    def test_bad_threshold_is_usage_error(self, tmp_path):
        assert bench_gate.main(["--threshold", "0"]) == 2

    def test_cross_backend_records_refused(self, tmp_path, capsys):
        """Two records of the same (mode, platform) family taken on
        different resolved JAX backends must NOT be diffed — the gate
        fails loudly instead of comparing apples to oranges (the silent
        CPU-fallback trap of rounds 1-5)."""
        old = make_record(tmp_path / "a.json", match=10.0)
        new = make_record(tmp_path / "b.json", match=10.0)
        for path, backend in ((old, "tpu"), (new, "cpu")):
            data = json.loads(pathlib.Path(path).read_text())
            data["backend"] = backend
            pathlib.Path(path).write_text(json.dumps(data))
        assert bench_gate.main([old, new]) == 1
        out = capsys.readouterr().out
        assert "REFUSED" in out and "different resolved JAX backends" in out

    def test_cross_backend_phase_refused(self, tmp_path, capsys):
        """One phase measured on a different backend (e.g. a device
        upgrade relay mixing records) refuses on its own even when the
        record-level backends agree or are absent."""
        old = make_record(tmp_path / "a.json", match=10.0, dru=2.0)
        new = make_record(tmp_path / "b.json", match=10.0, dru=2.0)
        for path, backend in ((old, "tpu"), (new, "cpu")):
            data = json.loads(pathlib.Path(path).read_text())
            data["phases"]["match"]["backend"] = backend
            pathlib.Path(path).write_text(json.dumps(data))
        assert bench_gate.main([old, new]) == 1
        assert "cross-backend" in capsys.readouterr().out

    def test_legacy_records_without_backend_still_compare(self, tmp_path):
        # records predating the backend stamp carry no label; the gate
        # compares them as before instead of refusing history
        old = make_record(tmp_path / "a.json", match=10.0)
        new = make_record(tmp_path / "b.json", match=50.0)
        assert bench_gate.main([old, new]) == 1  # real regression still fails


def mp_phase(p50=5.0, cores=1, speedup=1.0):
    return {"p50_ms": p50, "cores": cores,
            "rps_speedup_vs_sharded": speedup}


class TestMpSpeedupGate:
    """bench.py's control_plane_mp fleet-vs-sharded speedup self-gates
    when the recorded run had the cores to meet the 2.5x target
    (bench_gate.MP_GATE_MIN_CORES); below the floor it stays recorded,
    not gated — worker processes cannot beat the in-process plane
    without process parallelism."""

    def test_below_core_floor_is_informational(self, tmp_path, capsys):
        rec = make_record(tmp_path / "a.json",
                          control_plane_mp=mp_phase(cores=1, speedup=0.8))
        assert bench_gate.main([rec]) == 0
        out = capsys.readouterr().out
        assert "recorded, not gated" in out and "PASS" in out

    def test_enough_cores_meeting_target_passes(self, tmp_path, capsys):
        rec = make_record(tmp_path / "a.json",
                          control_plane_mp=mp_phase(cores=8, speedup=3.1))
        assert bench_gate.main([rec]) == 0
        assert "ok (target 2.5x)" in capsys.readouterr().out

    def test_enough_cores_below_target_fails(self, tmp_path, capsys):
        rec = make_record(tmp_path / "a.json",
                          control_plane_mp=mp_phase(cores=4, speedup=1.4))
        assert bench_gate.main([rec]) == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "mp speedup" in out

    def test_self_gate_also_runs_on_paired_records(self, tmp_path, capsys):
        # a family with a comparison pair must not skip the self-gate
        old = make_record(tmp_path / "a.json",
                          control_plane_mp=mp_phase(cores=8, speedup=3.0))
        new = make_record(tmp_path / "b.json",
                          control_plane_mp=mp_phase(cores=8, speedup=1.2))
        assert bench_gate.main([old, new]) == 1
        assert "mp speedup" in capsys.readouterr().out

    def test_differing_cores_pair_skips_timing(self, tmp_path, capsys):
        # 1-core p50 vs 8-core p50 is a hardware diff, not a regression;
        # the new record's own speedup still gates (and passes here)
        old = make_record(tmp_path / "a.json",
                          control_plane_mp=mp_phase(p50=5.0, cores=1,
                                                    speedup=0.9))
        new = make_record(tmp_path / "b.json",
                          control_plane_mp=mp_phase(p50=50.0, cores=8,
                                                    speedup=3.0))
        assert bench_gate.main([old, new]) == 0
        out = capsys.readouterr().out
        assert "differing core counts" in out and "PASS" in out
