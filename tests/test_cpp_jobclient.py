"""The C++ jobclient (native/jobclient/) against a live service process —
the role of the reference's Java JobClient tests: build the binary, then
submit/wait/show/kill over real HTTP."""
import shutil
import subprocess
import time

import pytest
import requests

from cook_tpu.components import build_process, shutdown, start_leader_duties
from cook_tpu.rest.server import free_port
from cook_tpu.utils.config import Settings

CLI = "native/cook_cli"


@pytest.fixture(scope="module")
def cli():
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    subprocess.run(["make", "-C", "native", "cook_cli"], check=True,
                   capture_output=True, timeout=180)
    return CLI


@pytest.fixture(scope="module")
def service():
    settings = Settings(
        port=free_port(),
        rank_interval_s=0.2, match_interval_s=0.2,
        clusters=[{"kind": "mock", "name": "m", "default_runtime_ms": 800,
                   "hosts": [{"node_id": "h", "mem": 8000, "cpus": 16}]}],
    )
    process = build_process(settings)
    start_leader_duties(process, block=False, on_loss=lambda: None)
    url = f"http://127.0.0.1:{settings.port}"
    # service reachable before clients hit it
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            requests.get(f"{url}/debug", timeout=1)
            break
        except requests.ConnectionError:
            time.sleep(0.1)
    yield url
    shutdown(process)


def run_cli(cli, url, *args, user="alice", timeout=60):
    return subprocess.run(
        [cli, "--url", url, "--user", user, *args],
        capture_output=True, text=True, timeout=timeout)


def test_submit_wait_show_roundtrip(cli, service):
    out = run_cli(cli, service, "submit", "echo hi", "256", "1")
    assert out.returncode == 0, out.stderr
    uuid = out.stdout.strip()
    assert len(uuid) == 36

    out = run_cli(cli, service, "wait", uuid, "30000")
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "completed"
    # the listener observed intermediate states on stderr
    assert "status:" in out.stderr

    out = run_cli(cli, service, "show", uuid)
    assert out.returncode == 0
    assert "completed" in out.stdout
    assert "host=h" in out.stdout


def test_kill(cli, service):
    out = run_cli(cli, service, "submit", "sleep 9999", "256", "1")
    uuid = out.stdout.strip()
    time.sleep(1)  # let it start
    out = run_cli(cli, service, "kill", uuid)
    assert out.returncode == 0, out.stderr
    deadline = time.time() + 10
    while time.time() < deadline:
        out = run_cli(cli, service, "show", uuid)
        if "completed" in out.stdout:
            break
        time.sleep(0.2)
    assert "completed" in out.stdout


def test_kill_authz_enforced(cli, service):
    """Another user cannot kill alice's job (403 surfaces as rc=1)."""
    out = run_cli(cli, service, "submit", "sleep 9999", "256", "1")
    uuid = out.stdout.strip()
    out = run_cli(cli, service, "kill", uuid, user="mallory")
    assert out.returncode == 1
    assert "403" in out.stderr
    run_cli(cli, service, "kill", uuid)  # cleanup as owner


def test_unknown_job_is_client_error(cli, service):
    out = run_cli(cli, service, "show",
                  "00000000-0000-0000-0000-000000000000")
    assert out.returncode == 1
    assert "404" in out.stderr
