"""The driver-facing gates in __graft_entry__ must not hang on a wedged
accelerator tunnel (round-1 failure: MULTICHIP_r01 rc=124 because
dryrun_multichip called jax.devices() in-process before any CPU fallback)."""
import os
import subprocess
import sys

import pytest

import __graft_entry__ as graft

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _FakeProbe:
    """Stand-in for subprocess.run inside _pin_usable_platform."""

    def __init__(self, stdout=None, exc=None):
        self.stdout = stdout
        self.exc = exc

    def __call__(self, *a, **kw):
        if self.exc is not None:
            raise self.exc
        class R:
            stdout = self.stdout
        return R()


def _forced_platform(monkeypatch, probe):
    calls = []
    monkeypatch.setattr(graft, "_pin_usable_platform", graft._pin_usable_platform)
    import jax

    monkeypatch.setattr(subprocess, "run", probe)
    monkeypatch.setattr(jax.config, "update",
                        lambda k, v: calls.append((k, v)))
    graft._pin_usable_platform(8)
    return calls


def test_pin_forces_cpu_when_probe_hangs(monkeypatch):
    probe = _FakeProbe(exc=subprocess.TimeoutExpired(cmd="jax", timeout=120))
    calls = _forced_platform(monkeypatch, probe)
    assert ("jax_platforms", "cpu") in calls


def test_pin_forces_cpu_when_accelerator_has_too_few_chips(monkeypatch):
    calls = _forced_platform(monkeypatch, _FakeProbe(stdout="1 tpu\n"))
    assert ("jax_platforms", "cpu") in calls


def test_pin_keeps_accelerator_when_probe_shows_enough_chips(monkeypatch):
    calls = _forced_platform(monkeypatch, _FakeProbe(stdout="8 tpu\n"))
    assert calls == []


def test_pin_forces_cpu_when_probe_reports_cpu(monkeypatch):
    calls = _forced_platform(monkeypatch, _FakeProbe(stdout="8 cpu\n"))
    assert ("jax_platforms", "cpu") in calls


def test_dryrun_multichip_subprocess_end_to_end():
    """The full 8-device gate, exactly as the driver invokes it, must pass in
    a fresh process with no accelerator reachable (axon disabled)."""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # disable accelerator registration
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    r = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('OK')"],
        cwd=REPO, env=env, timeout=600, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr[-4000:]
    assert "OK" in r.stdout
