"""Sharded-solve tests on the virtual 8-device CPU mesh: pool-axis sharding
and node-axis sharding must reproduce the single-device kernels exactly."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cook_tpu.ops.match import MatchProblem, greedy_match
from cook_tpu.parallel.mesh import (
    make_mesh,
    node_sharded_greedy_match,
    pool_sharded_dru,
    pool_sharded_match,
    shard_pools,
)
from tests.test_ops_parity import random_dru_problem, random_match_problem


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh()


def make_pool_batch(n_pools=8, j=64, n=16, seed=0):
    probs = []
    for p in range(n_pools):
        rng = np.random.default_rng(seed + p)
        demands, avail, totals, feasible = random_match_problem(rng, j=j, n=n)
        probs.append((demands, avail, totals, feasible))
    stack = lambda i: jnp.asarray(np.stack([p[i] for p in probs]))
    return MatchProblem(
        demands=stack(0),
        job_valid=jnp.ones((n_pools, j), dtype=bool),
        avail=stack(1),
        totals=stack(2),
        node_valid=jnp.ones((n_pools, n), dtype=bool),
        feasible=stack(3),
    )


def test_pool_sharded_match_parity(mesh):
    problems = make_pool_batch()
    problems = shard_pools(mesh, problems)
    got = pool_sharded_match(mesh, problems)
    want = jax.vmap(greedy_match)(problems)
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment)
    )


def test_pool_sharded_match_backend_knobs(mesh):
    """The sharded solve honors the configured backend + chunk knobs
    (bucketed here): all placements respect per-pool constraint masks."""
    problems = make_pool_batch()
    problems = shard_pools(mesh, problems)
    got = pool_sharded_match(mesh, problems, chunk=64, rounds=3, passes=3,
                             backend="bucketed")
    a = np.asarray(got.assignment)
    feas = np.asarray(problems.feasible)
    for p in range(a.shape[0]):
        placed = a[p] >= 0
        assert placed.sum() > 0
        assert feas[p][np.where(placed)[0], a[p][placed]].all()


def test_pool_sharded_dru_runs(mesh):
    from cook_tpu.ops.common import BIG, pad_to
    from cook_tpu.ops.dru import DruTasks, dru_rank

    pools = []
    for p in range(8):
        rng = np.random.default_rng(40 + p)
        user, mem, cpus, gpus, order_key, md, cd, gd = random_dru_problem(
            rng, t=128, u=8
        )
        pools.append((user, mem, cpus, gpus, order_key, md, cd, gd))
    tasks = DruTasks(
        user=jnp.asarray(np.stack([p[0] for p in pools]).astype(np.int32)),
        mem=jnp.asarray(np.stack([p[1] for p in pools])),
        cpus=jnp.asarray(np.stack([p[2] for p in pools])),
        gpus=jnp.asarray(np.stack([p[3] for p in pools])),
        order_key=jnp.asarray(np.stack([p[4] for p in pools])),
        valid=jnp.ones((8, 128), dtype=bool),
    )
    md = jnp.asarray(np.stack([p[5] for p in pools]))
    cd = jnp.asarray(np.stack([p[6] for p in pools]))
    gd = jnp.asarray(np.stack([p[7] for p in pools]))
    got = pool_sharded_dru(mesh, tasks, md, cd, gd)
    for p in range(8):
        single = dru_rank(
            jax.tree.map(lambda x: x[p], tasks), md[p], cd[p], gd[p]
        )
        np.testing.assert_allclose(
            np.asarray(got.dru[p]), np.asarray(single.dru), rtol=1e-5
        )


def test_node_sharded_match_parity(mesh):
    rng = np.random.default_rng(7)
    demands, avail, totals, feasible = random_match_problem(rng, j=96, n=64)
    j, n = feasible.shape
    problem = MatchProblem(
        demands=jnp.asarray(demands),
        job_valid=jnp.ones(j, dtype=bool),
        avail=jnp.asarray(avail),
        totals=jnp.asarray(totals),
        node_valid=jnp.ones(n, dtype=bool),
        feasible=jnp.asarray(feasible),
    )
    want = greedy_match(problem)
    got = node_sharded_greedy_match(mesh, problem)
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment)
    )
    np.testing.assert_allclose(
        np.asarray(got.new_avail), np.asarray(want.new_avail),
        rtol=1e-5, atol=1e-4,
    )


def test_node_sharded_chunked_match_parity(mesh):
    """The production chunked matcher with its candidate pass sharded
    over nodes: >=0.99 of the exact greedy packing, no oversubscription,
    and all placements respect the constraint mask."""
    from cook_tpu.ops import cpu_reference as ref
    from cook_tpu.parallel.mesh import node_sharded_chunked_match

    rng = np.random.default_rng(17)
    demands, avail, totals, feasible = random_match_problem(rng, j=256, n=64)
    j, n = feasible.shape
    problem = MatchProblem(
        demands=jnp.asarray(demands),
        job_valid=jnp.ones(j, dtype=bool),
        avail=jnp.asarray(avail),
        totals=jnp.asarray(totals),
        node_valid=jnp.ones(n, dtype=bool),
        feasible=jnp.asarray(feasible),
    )
    exact = greedy_match(problem)
    got = node_sharded_chunked_match(mesh, problem, chunk=64, rounds=3,
                                     kc=16, passes=3)
    a = np.asarray(got.assignment)
    qe = ref.packing_quality(demands, np.asarray(exact.assignment))
    q = ref.packing_quality(demands, a)
    assert np.all(np.asarray(got.new_avail) >= -1e-3)
    assert q["num_placed"] >= 0.99 * qe["num_placed"]
    assert q["cpus_placed"] >= 0.99 * qe["cpus_placed"]
    placed = a >= 0
    assert feasible[np.where(placed)[0], a[placed]].all()


def test_task_sharded_dru_parity(mesh):
    """Task-axis sharding: XLA distributes the sort/cumsum; results must
    match the single-device kernel exactly."""
    from cook_tpu.ops.dru import DruTasks, dru_rank
    from cook_tpu.parallel.mesh import task_sharded_dru

    rng = np.random.default_rng(77)
    t, u = 1024, 16
    user, mem, cpus, gpus, order_key, md, cd, gd = random_dru_problem(
        rng, t=t, u=u)
    tasks = DruTasks(
        user=jnp.asarray(user.astype(np.int32)),
        mem=jnp.asarray(mem.astype(np.float32)),
        cpus=jnp.asarray(cpus.astype(np.float32)),
        gpus=jnp.asarray(gpus.astype(np.float32)),
        order_key=jnp.asarray(order_key.astype(np.float32)),
        valid=jnp.ones(t, dtype=bool),
    )
    md, cd, gd = (jnp.asarray(x.astype(np.float32)) for x in (md, cd, gd))
    want = dru_rank(tasks, md, cd, gd)
    got = task_sharded_dru(mesh, tasks, md, cd, gd)
    np.testing.assert_allclose(np.asarray(got.dru), np.asarray(want.dru),
                               rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(got.order),
                                  np.asarray(want.order))


def test_invalid_pool_padding_no_phantom_output(mesh):
    """Regression for the invalid_match_problem padding edge: a pool (or
    hierarchical block) count NOT divisible by the mesh size pads with
    all-invalid lanes — those lanes must contribute ZERO assignments and
    leave their (zero) availability untouched, while the real lanes
    reproduce the single-device solve exactly."""
    from cook_tpu.parallel.mesh import invalid_match_problem

    real = make_pool_batch(n_pools=3, j=64, n=16, seed=21)
    pad = invalid_match_problem(64, 16, n_res=real.demands.shape[-1])
    problems = jax.tree.map(
        lambda r, d: jnp.concatenate(
            [r, jnp.broadcast_to(d, (5,) + d.shape)]),
        real, pad)
    problems = shard_pools(mesh, problems)
    got = pool_sharded_match(mesh, problems)
    a = np.asarray(got.assignment)
    assert (a[3:] == -1).all(), "padded lanes produced phantom matches"
    np.testing.assert_array_equal(np.asarray(got.new_avail[3:]), 0.0)
    want = jax.vmap(greedy_match)(real)
    np.testing.assert_array_equal(a[:3], np.asarray(want.assignment))


def test_pool_sharded_match_without_constraint_mask(mesh):
    """feasible=None batches (the hierarchical fine solve at XL sizes,
    where a [J, N] mask would be GBs) shard with a None spec lane."""
    real = make_pool_batch(n_pools=8, j=64, n=16, seed=33)
    unmasked = real._replace(feasible=None)
    unmasked = shard_pools(mesh, unmasked)
    got = pool_sharded_match(mesh, unmasked, chunk=64, rounds=3, passes=2,
                             kc=8)
    a = np.asarray(got.assignment)
    assert (a >= 0).sum() > 0
    assert np.all(np.asarray(got.new_avail) >= -1e-3)
